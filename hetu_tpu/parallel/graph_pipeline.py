"""Graph-driven pipeline parallelism over inhomogeneous stages.

Reference: the reference infers pipeline stages from per-node ``raw_ctx``
device groups (context.py:1430-1492 `get_pipeline_stage_info`), partitions
the graph into per-rank subgraphs, and drives them with host-side
schedulers — `SubExecutor4Gpipe` (gpipe_subexecutor.py:7, all-forward then
all-backward with per-micro-batch tensor maps) and `SubExecutor4Pipedream`
(pipedream_subexecutor.py:25, 1F1B) exchanging activations over NCCL P2P
(PipelineSend/Receive ops).

TPU redesign: each stage compiles to TWO jitted programs — a forward
(stage subgraph evaluated through graph/trace.py on the stage's devices)
and a rematerializing backward (``jax.vjp`` of the stage forward, so only
O(boundary) activations are stashed between fwd and bwd — the flush
schedules' weight-stashing is unnecessary because parameters don't change
mid-flush).  The host scheduler plays the reference's role: it slices the
batch into micro-batches, issues stage programs in GPipe or 1F1B order,
moves boundary activations/cotangents between stage device sets with
``jax.device_put`` (the ICI transfer that PipelineSend/Recv did over
NCCL), accumulates gradients across micro-batches (and across stages for
variables shared between stages, e.g. a tied LM head), and applies the
optimizer per stage.  JAX's async dispatch overlaps the stage programs:
issuing fwd(m=1, s=0) returns before fwd(m=0, s=1) finishes, so stages
genuinely run concurrently on their own devices.

Unlike parallel/pipeline.py (one SPMD program, homogeneous stacked
stages), stages here are arbitrary per-stage subgraphs — embedding stage,
N transformer stages, LM-head stage — driven from ``with ht.stage(i):``
annotations through the normal Executor API.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np
import jax
import jax.numpy as jnp

from ..graph.node import Op, PlaceholderOp, VariableOp, find_topo_sort
from ..graph.trace import TraceContext, evaluate


def _stage_of_annotation(raw_ctx):
    if raw_ctx is None:
        return None
    if isinstance(raw_ctx, (int, np.integer)):
        return int(raw_ctx)
    spec = getattr(raw_ctx, "spec", None)  # DeviceGroup(stage_idx)
    if isinstance(spec, (int, np.integer)):
        return int(spec)
    raise ValueError(f"unsupported raw_ctx for pipeline staging: {raw_ctx!r}")


def assign_stages(topo):
    """Infer a stage for every node from ``raw_ctx`` annotations.

    Interior ops: the annotation if present, else the max of the input
    stages (activations flow forward; reference stage inference walks
    raw_ctx the same direction).  Stages must be non-decreasing along
    edges.  Leaves (placeholders/variables) are not assigned — they are
    bound into every stage that consumes them.
    """
    stage_of = {}
    for n in topo:
        if isinstance(n, (PlaceholderOp, VariableOp)):
            continue
        from_inputs = max((stage_of[i] for i in n.inputs if i in stage_of),
                          default=0)
        s = _stage_of_annotation(n.raw_ctx)
        if s is None:
            s = from_inputs
        elif s < from_inputs:
            raise ValueError(
                f"op {n.name} annotated stage {s} but consumes a stage-"
                f"{from_inputs} activation; stages must be non-decreasing "
                "along dataflow edges")
        stage_of[n] = s
    return stage_of


class _StagePrograms:
    """The partitioned subgraph + compiled programs of one stage."""

    def __init__(self, idx):
        self.idx = idx
        self.topo = []            # stage-s ops, global topo order
        self.variables = []       # VariableOps bound into this stage
        self.placeholders = []    # PlaceholderOps fed into this stage
        self.acts_in = []         # earlier-stage op outputs consumed here
        self.acts_out = []        # op outputs consumed by later stages
        self.evals = []           # user eval nodes computed here
        self.loss = None          # the differentiated loss, if it lives here
        self.fwd = None
        self.bwd = None
        self.update = None
        self.opt_vars = []        # optimized variables homed on this stage
        self.device_put = None    # place an array onto this stage


class PipelineSubExecutor:
    """Executor subgraph run under an inhomogeneous-stage pipeline.

    Drop-in for graph/executor.SubExecutor when the Executor is built with
    ``pipeline=`` config: same ``run(feed_dict)`` contract, same shared
    ``executor.params`` / ``executor.opt_state`` stores.

    Config (Executor kwargs):
      pipeline   : 'gpipe' (all forwards, then all backwards — stashes
                   every micro's boundary activations) | '1f1b'
                   (pipedream-flush: each micro's backward issues as soon
                   as its forward drains, so ~n_stages micros of boundary
                   activations live instead of num_micro; numerics
                   identical)
      num_micro  : micro-batches per step (feeds split on axis 0; list
                   exceptions in non_batch_feeds)
      num_stages : stage count; default = max annotation + 1, or the
                   mesh's 'pp' axis size when a mesh is attached
      non_batch_feeds : placeholder names fed WHOLE to every micro-batch
                   (e.g. an [S, S] attention mask)
    """

    def __init__(self, name, eval_nodes, executor):
        from ..optim.optimizer import OptimizerOp
        self.name = name
        self.executor = executor
        self.eval_nodes = list(eval_nodes)
        self.schedule = executor.config.get("pipeline", "gpipe")
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"pipeline schedule {self.schedule!r}")
        self.n_micro = int(executor.config.get("num_micro", 1))

        self.opt_ops = [n for n in self.eval_nodes
                        if isinstance(n, OptimizerOp)]
        if len(self.opt_ops) > 1:
            raise ValueError("pipeline supports one OptimizerOp per "
                             "subgraph")
        self.user_outputs = [n for n in self.eval_nodes
                             if not isinstance(n, OptimizerOp)]
        self.opt_op = self.opt_ops[0] if self.opt_ops else None
        self.training = self.opt_op is not None
        if self.opt_op is not None and getattr(self.opt_op, "sparse", None):
            # refuse, don't silently skip: stage homing and the backward
            # builders consult var_list only, so a sparse-flagged table
            # would train NOTHING under the pipeline path
            raise NotImplementedError(
                "lazy sparse optimizer updates (minimize(sparse_vars=...)) "
                "are not supported under the graph pipeline; use the "
                "dense path or the PS embedding subsystem")

        roots = list(self.user_outputs)
        self.loss = None
        if self.opt_op is not None:
            self.loss = self.opt_op.loss
            if self.loss is None:
                raise ValueError(
                    "pipeline training needs OptimizerOp.loss (build the "
                    "train op with opt.minimize(loss))")
            if self.loss not in roots:
                roots.append(self.loss)
        self.topo = find_topo_sort(roots)
        self.placeholders = [n for n in self.topo
                             if isinstance(n, PlaceholderOp)]
        self.variables = [n for n in self.topo if isinstance(n, VariableOp)]
        if any(hasattr(p, "ps_embedding") for p in self.placeholders):
            raise NotImplementedError(
                "PS-backed embeddings under the pipeline executor")

        stage_of = assign_stages(self.topo)
        n_stages = executor.config.get("num_stages")
        if n_stages is None:
            if executor.mesh is not None and "pp" in executor.mesh.axis_names:
                n_stages = executor.mesh.shape["pp"]
            else:
                n_stages = max(stage_of.values(), default=0) + 1
        self.n_stages = int(n_stages)
        bad = {n.name: s for n, s in stage_of.items()
               if s >= self.n_stages}
        if bad:
            raise ValueError(
                f"ops annotated beyond num_stages={self.n_stages}: {bad}")
        self._partition(stage_of)
        self._plan_devices()
        self._built = False

    # -- graph partitioning ------------------------------------------------
    def _partition(self, stage_of):
        consumers = defaultdict(list)
        for n in self.topo:
            for i in n.inputs:
                consumers[i].append(n)
        self.stages = [_StagePrograms(s) for s in range(self.n_stages)]
        for n in self.topo:
            if isinstance(n, (PlaceholderOp, VariableOp)):
                seen = set()
                for c in consumers[n]:
                    s = stage_of[c]
                    if s in seen:
                        continue
                    seen.add(s)
                    st = self.stages[s]
                    if isinstance(n, VariableOp):
                        st.variables.append(n)
                    else:
                        st.placeholders.append(n)
                continue
            s = stage_of[n]
            st = self.stages[s]
            st.topo.append(n)
            later = sorted({stage_of[c] for c in consumers[n]
                            if stage_of[c] > s})
            if later:
                st.acts_out.append(n)
                seen = set()
                for c in consumers[n]:
                    cs = stage_of[c]
                    if cs > s and cs not in seen:
                        seen.add(cs)
                        self.stages[cs].acts_in.append(n)
            if n in self.user_outputs:
                st.evals.append(n)
            if n is self.loss:
                st.loss = n
        # optimized variables are HOMED on their first consuming stage
        # (updates run there; stages sharing the variable send its grad)
        if self.opt_op is not None:
            homed = set()
            for st in self.stages:
                for v in st.variables:
                    if v in self.opt_op.var_list and v not in homed:
                        homed.add(v)
                        st.opt_vars.append(v)
            missing = [v.name for v in self.opt_op.var_list
                       if v not in homed]
            if missing:
                raise ValueError(
                    f"optimized variables unused by the graph: {missing}")

    # -- device planning ---------------------------------------------------
    def _plan_devices(self):
        """Per-stage placement: the mesh's pp-slice s (with any remaining
        axes as an intra-stage submesh), else device s of the default
        backend, else no placement (single-device/CPU tests)."""
        mesh = self.executor.mesh
        self._stage_meshes = [None] * self.n_stages
        if mesh is not None and "pp" in mesh.axis_names:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec
            assert mesh.axis_names[0] == "pp", \
                "pipeline mesh must have 'pp' as its leading axis"
            assert mesh.shape["pp"] >= self.n_stages
            rest = mesh.axis_names[1:]
            for st in self.stages:
                block = mesh.devices[st.idx]
                if rest:
                    sub = Mesh(block, rest)
                    self._stage_meshes[st.idx] = sub
                    sh = NamedSharding(sub, PartitionSpec())
                    st.device_put = (
                        lambda x, sh=sh: jax.device_put(x, sh))
                else:
                    dev = block.item() if hasattr(block, "item") else block
                    st.device_put = (
                        lambda x, dev=dev: jax.device_put(x, dev))
        else:
            devs = jax.devices()
            for st in self.stages:
                dev = devs[st.idx % len(devs)]
                st.device_put = lambda x, dev=dev: jax.device_put(x, dev)

    # -- program construction ----------------------------------------------
    def _cast(self, x):
        cd = self.executor.compute_dtype
        if cd is not None and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(cd)
        return x

    def _make_fwd(self, st):
        out_nodes = list(st.acts_out)
        for n in st.evals:
            if n not in out_nodes:
                out_nodes.append(n)
        if st.loss is not None and st.loss not in out_nodes:
            out_nodes.append(st.loss)
        training = self.training
        mesh = self._stage_meshes[st.idx]

        def fwd(params, feeds, in_acts, key):
            ctx = TraceContext(key=key, training=training, mesh=mesh)
            bindings = {}
            for v in st.variables:
                bindings[v] = self._cast(params[v.name])
            for p in st.placeholders:
                bindings[p] = feeds[p.name]
            for u in st.acts_in:
                bindings[u] = in_acts[u.name]
            vals, _ = evaluate(out_nodes, bindings, ctx, topo=st.topo)
            # stateful ops (batchnorm running stats, assign): thread the
            # new values out; the scheduler chains them across micro-
            # batches and writes them back after the step (reference
            # gpipe_subexecutor.py:7 schedules arbitrary subgraphs)
            updates = {v.name: val for v, val in ctx.updates.items()}
            return {n.name: v for n, v in zip(out_nodes, vals)}, updates

        return jax.jit(fwd), out_nodes

    def _make_bwd(self, st):
        """Rematerializing backward: jax.vjp over (params, diff acts_in)
        of the stage's differentiable outputs."""
        diff_outs = list(st.acts_out)
        if st.loss is not None and st.loss not in diff_outs:
            diff_outs.append(st.loss)
        diff_vars = [v for v in st.variables
                     if self.opt_op is not None
                     and v in self.opt_op.var_list]
        training = self.training
        mesh = self._stage_meshes[st.idx]

        def bwd(params, feeds, in_acts, cts, key):
            def f(var_vals, act_vals):
                ctx = TraceContext(key=key, training=training, mesh=mesh)
                bindings = {}
                for v in st.variables:
                    bindings[v] = self._cast(params[v.name])
                for v, val in zip(diff_vars, var_vals):
                    bindings[v] = self._cast(val)
                for p in st.placeholders:
                    bindings[p] = feeds[p.name]
                bindings.update(dict(zip(st.acts_in, act_vals)))
                vals, _ = evaluate(diff_outs, bindings, ctx, topo=st.topo)
                return tuple(vals)

            primb_vars = tuple(params[v.name] for v in diff_vars)
            prim_acts = tuple(in_acts[u.name] for u in st.acts_in)
            _, vjp_fn = jax.vjp(f, primb_vars, prim_acts)
            ct_vals = tuple(cts[n.name] for n in diff_outs)
            gvars, gacts = vjp_fn(ct_vals)
            return ({v.name: g for v, g in zip(diff_vars, gvars)},
                    {u.name: g for u, g in zip(st.acts_in, gacts)})

        return jax.jit(bwd), diff_outs, diff_vars

    def _make_update(self, st):
        if not st.opt_vars:
            return None
        opt = self.opt_op.optimizer
        names = [v.name for v in st.opt_vars]

        def update(params, slots, grads, step, scale):
            lr = opt.lr.get(step)
            new_params, new_slots = {}, {}
            for name in names:
                g = grads[name].astype(params[name].dtype) * scale
                p, ns = opt.apply_dense(params[name], g, slots[name], lr,
                                        step)
                new_params[name] = p
                new_slots[name] = ns
            return new_params, new_slots

        return jax.jit(update, donate_argnums=(0, 1))

    def _build(self):
        ex = self.executor
        for st in self.stages:
            st.fwd, st.out_nodes = self._make_fwd(st)
            if self.training:
                st.bwd, st.diff_outs, st.diff_vars = self._make_bwd(st)
                st.update = self._make_update(st)
        # home each parameter onto the first stage that consumes it
        placed = set()
        for st in self.stages:
            for v in st.variables:
                if v.name not in placed:
                    placed.add(v.name)
                    ex.params[v.name] = st.device_put(ex.params[v.name])
        self._built = True

    # -- the schedule ------------------------------------------------------
    def _split_feeds(self, feed_dict):
        feeds = {}
        feed_dict = feed_dict or {}
        for node, value in feed_dict.items():
            name = node.name if isinstance(node, Op) else node
            feeds[name] = value
        for p in self.placeholders:
            if p.name not in feeds and hasattr(p, "auto_feed"):
                feeds[p.name] = p.auto_feed(self.name)
        missing = [p.name for p in self.placeholders if p.name not in feeds]
        if missing:
            raise ValueError(f"missing feeds for placeholders: {missing}")
        m = self.n_micro
        # feeds named in config 'non_batch_feeds' (e.g. a [S, S] attention
        # mask whose leading dim is NOT the batch) are replicated to every
        # micro-batch instead of split
        non_batch = set(self.executor.config.get("non_batch_feeds", ()))
        per_micro = [dict() for _ in range(m)]
        for p in self.placeholders:
            v = np.asarray(feeds[p.name])
            if p.name in non_batch or not v.shape:
                whole = self._cast(jnp.asarray(v, dtype=p.dtype))
                for i in range(m):
                    per_micro[i][p.name] = whole
                continue
            if v.shape[0] % m == 0:
                chunks = np.split(v, m, axis=0)
            else:
                raise ValueError(
                    f"feed {p.name} (shape {v.shape}) not splittable into "
                    f"{m} micro-batches along axis 0; list it in "
                    "non_batch_feeds if it should be fed whole")
            for i in range(m):
                per_micro[i][p.name] = self._cast(
                    jnp.asarray(chunks[i], dtype=p.dtype))
        return per_micro

    def _stage_pviews(self, params):
        """Per-stage parameter views, built ONCE per pass: device_put is a
        no-op for home params and an ICI transfer for variables shared
        across stages (e.g. a tied LM head) — hoisting it out of the
        micro loop issues that transfer once per stage, not per micro."""
        return [{v.name: st.device_put(params[v.name])
                 for v in st.variables} for st in self.stages]

    def _fwd_micro(self, i, s, pviews, stage_feeds, acts, evals, keys):
        st = self.stages[s]
        ins = {u.name: st.device_put(acts[i][u.name])
               for u in st.acts_in}
        outs, updates = st.fwd(pviews[s], stage_feeds[i][s], ins, keys[i])
        if updates:
            # chain running-state (batchnorm stats, assigns) through the
            # micro-batch sequence: the next micro's forward on this stage
            # sees this micro's EMA, and the final values write back to
            # executor params after the step
            pviews[s] = {**pviews[s], **updates}
            self._pending_state.update(updates)
        for n in st.out_nodes:
            if n in st.acts_out:
                acts[i][n.name] = outs[n.name]
            if n in st.evals or n is st.loss:
                evals[i][n.name] = outs[n.name]

    def _bwd_micro(self, i, pviews, stage_feeds, acts, evals, keys,
                   grad_acc, loss_ct):
        """Issue micro ``i``'s backward chain (last stage → first) and
        release its boundary activations."""
        cts = defaultdict(list)
        for s in reversed(range(self.n_stages)):
            st = self.stages[s]
            if not st.diff_vars and not st.acts_in:
                continue
            ins = {u.name: st.device_put(acts[i][u.name])
                   for u in st.acts_in}
            ct_in = {}
            for n in st.diff_outs:
                if n is st.loss and n not in st.acts_out:
                    ct_in[n.name] = jnp.asarray(
                        loss_ct, evals[i][n.name].dtype)
                else:
                    pend = cts.pop(n.name, None)
                    ct_in[n.name] = (
                        self._accum(pend, st.device_put) if pend else
                        st.device_put(jnp.zeros_like(acts[i][n.name])))
                    if n is st.loss:
                        ct_in[n.name] = ct_in[n.name] + jnp.asarray(
                            loss_ct, ct_in[n.name].dtype)
            gvars, gacts = st.bwd(pviews[s], stage_feeds[i][s], ins,
                                  ct_in, keys[i])
            for name, g in gvars.items():
                grad_acc.setdefault(name, []).append(g)
            for name, g in gacts.items():
                cts[name].append(g)
        acts[i].clear()   # boundary activations of micro i are consumed

    def run(self, feed_dict=None, convert_to_numpy_ret_vals=False):
        if not self._built:
            self._build()
        ex = self.executor
        m = self.n_micro
        per_micro = self._split_feeds(feed_dict)
        base = jax.random.fold_in(ex._base_key, ex._global_step)
        ex._global_step += 1
        keys = [jax.random.fold_in(base, i) for i in range(m)]

        stage_feeds = [[{p.name: st.device_put(per_micro[i][p.name])
                         for p in st.placeholders}
                        for st in self.stages] for i in range(m)]
        params = ex.params
        pviews = self._stage_pviews(params)
        self._pending_state = {}               # stateful-op write-backs

        acts = [dict() for _ in range(m)]      # micro -> {name: value}
        evals = [dict() for _ in range(m)]     # micro -> {name: value}
        grad_acc = {}                          # var name -> [values]
        loss_ct = 1.0 / m                      # step loss = mean of micros

        # wavefront issue order: (micro+stage) diagonal — stage s of micro
        # i is issued right after its dependencies, and JAX async dispatch
        # overlaps the stage programs across their device sets (the role
        # of the reference's per-rank schedulers + NCCL group batching).
        # schedule='1f1b' (pipedream-flush, pipedream_subexecutor.py:25)
        # additionally issues each micro's FULL backward chain as soon as
        # its forward leaves the last stage, releasing that micro's
        # boundary activations — at most ~n_stages micros live at once
        # instead of all n_micro (gpipe_subexecutor.py:7 stashes all).
        order = sorted(((i, s) for i in range(m)
                        for s in range(self.n_stages)),
                       key=lambda t: (t[0] + t[1], t[1]))
        for i, s in order:
            self._fwd_micro(i, s, pviews, stage_feeds, acts, evals, keys)
            if (self.training and self.schedule == "1f1b"
                    and s == self.n_stages - 1):
                self._bwd_micro(i, pviews, stage_feeds, acts, evals, keys,
                                grad_acc, loss_ct)
        if self.training and self.schedule == "gpipe":
            for i in reversed(range(m)):
                self._bwd_micro(i, pviews, stage_feeds, acts, evals, keys,
                                grad_acc, loss_ct)

        # ---- optimizer update per stage --------------------------------
        if self.training:
            opt_state = ex.opt_state[self.opt_op.name]
            step = opt_state["step"]
            scale = jnp.asarray(1.0)
            if self.opt_op.clip_global_norm is not None:
                sq = []
                for name, gs in grad_acc.items():
                    g = self._accum(gs, self._home_put(name))
                    grad_acc[name] = [g]
                    # device-resident partial: a host np.asarray here
                    # would sync mid-step and stall the async pipeline
                    sq.append(jnp.sum(jnp.square(g.astype(jnp.float32))))
                home = self.stages[0].device_put
                total = home(sq[0])
                for p in sq[1:]:
                    total = total + home(p)
                gnorm = jnp.sqrt(total)
                scale = jnp.minimum(
                    1.0, self.opt_op.clip_global_norm / (gnorm + 1e-6))
            new_slots = dict(opt_state["slots"])
            for st in self.stages:
                if st.update is None:
                    continue
                pview = {v.name: params[v.name] for v in st.opt_vars}
                sview = {v.name: opt_state["slots"][v.name]
                         for v in st.opt_vars}
                gview = {v.name: self._accum(grad_acc[v.name],
                                             st.device_put)
                         for v in st.opt_vars}
                newp, news = st.update(pview, sview, gview,
                                       st.device_put(step),
                                       st.device_put(scale))
                params.update(newp)
                new_slots.update(news)
            ex.opt_state[self.opt_op.name] = {
                "step": step + 1, "slots": new_slots}

        # stateful-op results (batchnorm running stats, assigns): the
        # last micro's chained value becomes the step's new state
        if self._pending_state:
            params.update(self._pending_state)
            self._pending_state = {}

        # ---- outputs ---------------------------------------------------
        vals = []
        for n in self.eval_nodes:
            if n is self.opt_op:
                vals.append(None)
                continue
            # all micro values of one node come from the SAME stage (and
            # device), so aggregation runs on-device — no host bounce
            per = [evals[i][n.name] for i in range(m)]
            if per[0].ndim == 0:
                v = jnp.mean(jnp.stack(
                    [x.astype(jnp.float32) for x in per])).astype(
                        per[0].dtype)
            else:
                v = jnp.concatenate(per, axis=0)
            vals.append(np.asarray(v) if convert_to_numpy_ret_vals else v)
        return vals

    def _home_put(self, var_name):
        for st in self.stages:
            for v in st.opt_vars:
                if v.name == var_name:
                    return st.device_put
        return self.stages[0].device_put

    @staticmethod
    def _accum(parts, device_put):
        """Sum contributions that may live on different stage devices."""
        if len(parts) == 1:
            return device_put(parts[0])
        total = device_put(parts[0])
        for p in parts[1:]:
            total = total + device_put(p)
        return total

    def profile(self, feed_dict=None, repeats=10):
        import time
        self.run(feed_dict)
        start = time.perf_counter()
        for _ in range(repeats):
            out = self.run(feed_dict)
        jax.block_until_ready([o for o in out if o is not None])
        return (time.perf_counter() - start) / repeats
