"""Collective communication over mesh axes.

Reference: /root/reference/src/communication/mpi_nccl_communication.cu — MPI-
bootstrapped flat + grouped NCCL communicators with allreduce/reduce/bcast/
allgather/reducescatter/p2p/alltoall and a hierarchical (node-leader)
alltoall; Python face in python/hetu/communicator/mpi_nccl_comm.py.

TPU equivalents are the XLA collectives over ICI/DCN, invoked inside
`shard_map` over named mesh axes.  A "grouped communicator" is just a mesh
sub-axis: every call below takes `axis_name` (or a tuple for multi-axis
groups), which is the TPU analogue of `ncclGroupInit` sub-communicators
(mpi_nccl_comm.py:157).  The hierarchical a2a (H_A2A, node-leader staging)
becomes a two-stage all_to_all over ('dcn', 'ici') axes: stage within the
fast axis first, then across the slow axis — same bandwidth shape as the
reference's gather→a2a→scatter without explicit leader ranks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..platform import shard_map


# -- primitive wrappers (valid inside shard_map/pmapped code) --------------

def all_reduce(x, axis_name, op="sum"):
    """reference: _ncclAllReduce (mpi_nccl_communication.cu:137)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(op)


def all_gather(x, axis_name, axis=0, tiled=True):
    """reference: dlarrayAllGather (mpi_nccl_comm.py:307)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    """reference: dlarrayReduceScatter (mpi_nccl_comm.py:311)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name, split_axis, concat_axis):
    """reference: dlarrayAllToAll (mpi_nccl_comm.py:330) — NCCL send/recv
    loop; on TPU a single ICI all_to_all."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def hierarchical_all_to_all(x, outer_axis, inner_axis, outer_size,
                            inner_size, axis=0):
    """Two-level a2a (reference HAllToAll: node-leader gather → inter-node
    a2a → scatter, mpi_nccl_comm.py:334 + H_A2A_LayoutTransform.cu).

    Drop-in equivalent of a flat ``all_to_all`` over the combined
    (outer, inner) axis with flat rank = o * inner_size + i, but with the
    traffic staged: first within the fast inner axis (ICI), then across the
    slow outer axis (DCN).  Local stride-permutes between stages keep the
    piece→destination mapping identical to the flat collective (verified
    against it in tests/test_parallel.py).
    """
    No, Ni = outer_size, inner_size
    x = jnp.moveaxis(x, axis, 0)
    S = x.shape[0]
    assert S % (No * Ni) == 0, f"axis size {S} not divisible by {No * Ni}"
    piece = S // (No * Ni)
    rest = x.shape[1:]
    # group pieces by inner destination: [No_dest, Ni_dest, p] -> [Ni_dest,...]
    x = x.reshape(No, Ni, piece, *rest)
    x = jnp.swapaxes(x, 0, 1)
    # stage 1 (ICI): route by inner destination
    x = lax.all_to_all(x, inner_axis, split_axis=0, concat_axis=0,
                       tiled=True)
    # now [Ni_src, No_dest, p]; route by outer destination
    x = jnp.swapaxes(x, 0, 1)
    x = lax.all_to_all(x, outer_axis, split_axis=0, concat_axis=0,
                       tiled=True)
    # now [No_src, Ni_src, p] == flat source-rank order
    x = x.reshape(S, *rest)
    return jnp.moveaxis(x, 0, axis)


def broadcast(x, axis_name, src=0):
    """reference: dlarrayBroadcast (mpi_nccl_comm.py:303)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def reduce_(x, axis_name, dst=0, op="sum"):
    """reference: dlarrayNcclReduce (mpi_nccl_comm.py:299).  SPMD has no
    single-owner tensors; the reduced value lands on every shard but callers
    may mask to dst for parity semantics."""
    return all_reduce(x, axis_name, op)


def ppermute(x, axis_name, perm):
    """Point-to-point ring/permute (reference PipelineSend/Recv pairs,
    gpu_ops/PipelineSend.py — batched NCCL p2p)."""
    return lax.ppermute(x, axis_name, perm)


def send_next(x, axis_name, n):
    """Rotate +1 along a ring of size n (pipeline stage handoff)."""
    return lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def send_prev(x, axis_name, n):
    return lax.ppermute(x, axis_name, [(i, (i - 1) % n) for i in range(n)])


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def varying(x, axes):
    """Mark an array as device-varying over mesh axes (scan carries that
    start replicated but become shard-dependent need this under shard_map's
    varying-manual-axes checks; no-op where lax.pcast is unavailable)."""
    try:
        return lax.pcast(x, tuple(axes), to="varying")
    except (AttributeError, TypeError):
        return x


def _quantize(x, scale, qmax, itype):
    return jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax).astype(itype)


def _quantized_psum(x, axis_name, bits):
    """Returns (reduced, sent_local) — sent_local is the value this
    replica is accountable for delivering (stage-1 payload minus its own
    shard's stage-2 re-quantization error), so ``x_c - sent_local`` in
    error_feedback carries EXACTLY the undelivered mass.

    All quantize/dequantize/accumulate arithmetic runs in f32 (bf16
    inputs would cap bits=16 at bf16's 8 mantissa bits); only the final
    outputs cast back to x.dtype."""
    assert bits in (8, 16)
    qmax = float(2 ** (bits - 1) - 1)
    itype = jnp.int8 if bits == 8 else jnp.int16
    n = jax.lax.psum(1, axis_name)
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % n
    flat_p = jnp.pad(flat, (0, pad))
    # stage 1: shared scale; int payload rides all_to_all (pure data
    # movement — the WIRE carries int8/int16, unlike psum(int32) whose
    # accumulation dtype is also its wire dtype)
    scale1 = jnp.maximum(
        jax.lax.pmax(jnp.max(jnp.abs(flat_p)), axis_name), 1e-30)
    q1 = _quantize(flat_p, scale1, qmax, itype)
    shards = jax.lax.all_to_all(q1.reshape(n, -1), axis_name,
                                split_axis=0, concat_axis=0, tiled=True)
    # local accumulation in int32 (max |sum| = n * qmax, no overflow)
    local = shards.astype(jnp.int32).sum(0)
    r = local.astype(jnp.float32) * (scale1 / qmax)
    # stage 2: re-quantize the reduced shard for the gather leg
    scale2 = jnp.maximum(jax.lax.pmax(jnp.max(jnp.abs(r)), axis_name),
                         1e-30)
    q2 = _quantize(r, scale2, qmax, itype)
    g = jax.lax.all_gather(q2, axis_name, tiled=True)
    out_flat = g.astype(jnp.float32) * (scale2 / qmax)
    out = out_flat[:flat.shape[0]].reshape(x.shape).astype(x.dtype)
    sent1 = q1.astype(jnp.float32) * (scale1 / qmax)
    # my shard's stage-2 error is MINE to re-send next step: r_i equals
    # the exact sum of everyone's dequantized stage-1 payloads at shard
    # i, so charging err2_i to replica i's ledger makes
    # sum_replicas(sent) == what was actually delivered, elementwise
    chunk = r.shape[0]
    err2 = r - q2.astype(jnp.float32) * (scale2 / qmax)
    off = (jax.lax.axis_index(axis_name) * chunk,)
    sent_eff = jax.lax.dynamic_update_slice(
        sent1, jax.lax.dynamic_slice(sent1, off, (chunk,)) - err2, off)
    sent = sent_eff[:flat.shape[0]].reshape(x.shape).astype(x.dtype)
    return out, sent


def quantized_psum(x, axis_name, bits=8):
    """Bandwidth-reduced gradient all-reduce (EQuARX-style,
    arXiv:2506.17615 — retrieved technique; beyond the reference's comm
    backend): int8/int16 payloads on BOTH legs (all_to_all + all_gather,
    each (n-1)/n·B bytes of int vs the fp32 ring psum — ~4× less wire
    traffic at bits=8), int32 local accumulation, two pmax'd shared
    scales.  LOSSY — pair with ``error_feedback`` so quantization error
    carries into the next step.  Opt-in; nothing routes through this by
    default."""
    out, _ = _quantized_psum(x, axis_name, bits)
    return out


def error_feedback(x, residual, axis_name, bits=8):
    """quantized_psum with residual carry: returns (reduced, new_residual).
    The caller threads ``residual`` (zeros-like at step 0) through its
    step state; ``x + residual`` is quantized, and the part this replica
    failed to transmit (stage-1 error) becomes the next residual."""
    xc = x + residual
    reduced, sent = _quantized_psum(xc, axis_name, bits)
    return reduced, xc - sent


# -- host-level helpers ----------------------------------------------------

def sharded_fn(mesh, in_specs, out_specs, fn):
    """shard_map wrapper with hetu-style spec objects allowed."""
    from .mesh import DistState

    def norm(s):
        if isinstance(s, DistState):
            return s.to_pspec()
        return s

    return shard_map(fn, mesh=mesh,
                     in_specs=jax.tree_util.tree_map(
                         norm, in_specs,
                         is_leaf=lambda x: isinstance(x, (P, DistState))),
                     out_specs=jax.tree_util.tree_map(
                         norm, out_specs,
                         is_leaf=lambda x: isinstance(x, (P, DistState))))
