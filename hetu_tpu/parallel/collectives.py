"""Collective communication over mesh axes.

Reference: /root/reference/src/communication/mpi_nccl_communication.cu — MPI-
bootstrapped flat + grouped NCCL communicators with allreduce/reduce/bcast/
allgather/reducescatter/p2p/alltoall and a hierarchical (node-leader)
alltoall; Python face in python/hetu/communicator/mpi_nccl_comm.py.

TPU equivalents are the XLA collectives over ICI/DCN, invoked inside
`shard_map` over named mesh axes.  A "grouped communicator" is just a mesh
sub-axis: every call below takes `axis_name` (or a tuple for multi-axis
groups), which is the TPU analogue of `ncclGroupInit` sub-communicators
(mpi_nccl_comm.py:157).  The hierarchical a2a (H_A2A, node-leader staging)
becomes a two-stage all_to_all over ('dcn', 'ici') axes: stage within the
fast axis first, then across the slow axis — same bandwidth shape as the
reference's gather→a2a→scatter without explicit leader ranks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map


# -- primitive wrappers (valid inside shard_map/pmapped code) --------------

def all_reduce(x, axis_name, op="sum"):
    """reference: _ncclAllReduce (mpi_nccl_communication.cu:137)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(op)


def all_gather(x, axis_name, axis=0, tiled=True):
    """reference: dlarrayAllGather (mpi_nccl_comm.py:307)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    """reference: dlarrayReduceScatter (mpi_nccl_comm.py:311)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name, split_axis, concat_axis):
    """reference: dlarrayAllToAll (mpi_nccl_comm.py:330) — NCCL send/recv
    loop; on TPU a single ICI all_to_all."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def hierarchical_all_to_all(x, outer_axis, inner_axis, outer_size,
                            inner_size, axis=0):
    """Two-level a2a (reference HAllToAll: node-leader gather → inter-node
    a2a → scatter, mpi_nccl_comm.py:334 + H_A2A_LayoutTransform.cu).

    Drop-in equivalent of a flat ``all_to_all`` over the combined
    (outer, inner) axis with flat rank = o * inner_size + i, but with the
    traffic staged: first within the fast inner axis (ICI), then across the
    slow outer axis (DCN).  Local stride-permutes between stages keep the
    piece→destination mapping identical to the flat collective (verified
    against it in tests/test_parallel.py).
    """
    No, Ni = outer_size, inner_size
    x = jnp.moveaxis(x, axis, 0)
    S = x.shape[0]
    assert S % (No * Ni) == 0, f"axis size {S} not divisible by {No * Ni}"
    piece = S // (No * Ni)
    rest = x.shape[1:]
    # group pieces by inner destination: [No_dest, Ni_dest, p] -> [Ni_dest,...]
    x = x.reshape(No, Ni, piece, *rest)
    x = jnp.swapaxes(x, 0, 1)
    # stage 1 (ICI): route by inner destination
    x = lax.all_to_all(x, inner_axis, split_axis=0, concat_axis=0,
                       tiled=True)
    # now [Ni_src, No_dest, p]; route by outer destination
    x = jnp.swapaxes(x, 0, 1)
    x = lax.all_to_all(x, outer_axis, split_axis=0, concat_axis=0,
                       tiled=True)
    # now [No_src, Ni_src, p] == flat source-rank order
    x = x.reshape(S, *rest)
    return jnp.moveaxis(x, 0, axis)


def broadcast(x, axis_name, src=0):
    """reference: dlarrayBroadcast (mpi_nccl_comm.py:303)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def reduce_(x, axis_name, dst=0, op="sum"):
    """reference: dlarrayNcclReduce (mpi_nccl_comm.py:299).  SPMD has no
    single-owner tensors; the reduced value lands on every shard but callers
    may mask to dst for parity semantics."""
    return all_reduce(x, axis_name, op)


def ppermute(x, axis_name, perm):
    """Point-to-point ring/permute (reference PipelineSend/Recv pairs,
    gpu_ops/PipelineSend.py — batched NCCL p2p)."""
    return lax.ppermute(x, axis_name, perm)


def send_next(x, axis_name, n):
    """Rotate +1 along a ring of size n (pipeline stage handoff)."""
    return lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def send_prev(x, axis_name, n):
    return lax.ppermute(x, axis_name, [(i, (i - 1) % n) for i in range(n)])


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def varying(x, axes):
    """Mark an array as device-varying over mesh axes (scan carries that
    start replicated but become shard-dependent need this under shard_map's
    varying-manual-axes checks; no-op where lax.pcast is unavailable)."""
    try:
        return lax.pcast(x, tuple(axes), to="varying")
    except (AttributeError, TypeError):
        return x


# -- host-level helpers ----------------------------------------------------

def sharded_fn(mesh, in_specs, out_specs, fn):
    """shard_map wrapper with hetu-style spec objects allowed."""
    from .mesh import DistState

    def norm(s):
        if isinstance(s, DistState):
            return s.to_pspec()
        return s

    return shard_map(fn, mesh=mesh,
                     in_specs=jax.tree_util.tree_map(
                         norm, in_specs,
                         is_leaf=lambda x: isinstance(x, (P, DistState))),
                     out_specs=jax.tree_util.tree_map(
                         norm, out_specs,
                         is_leaf=lambda x: isinstance(x, (P, DistState))))
