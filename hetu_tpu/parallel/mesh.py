"""Device mesh + per-tensor layout algebra.

Reference: /root/reference/python/hetu/context.py — `DeviceGroup` (:28) names
device sets, `NodeStatus` (:248) is the layout spec: ``state`` (dim→#splits),
``duplicate`` (#replicas), ``partial`` (#partial-sums), ``order`` (device
ordering).  A graph-rewrite pass (:1469) compares producer/consumer states
and materializes collectives by hand.

TPU redesign: the mesh is a `jax.sharding.Mesh` over named axes (dp/tp/pp/
sp/ep/cp...), and the layout spec `DistState` maps tensor dims to mesh axes —
exactly GSPMD's model, so *lowering is the compiler's job*: annotate
placeholders/variables (executor in_shardings) and constraint nodes
(dispatch_op), and XLA inserts the all-reduce/all-gather/reduce-scatter/
collective-permute the reference's cross_send/cross_receive emitted manually.
``partial`` maps to psum-pending values inside shard_map blocks
(parallel/tensor_parallel.py) where we take explicit control.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes, devices=None):
    """Create a Mesh from {'axis': size} (insertion order = device-major
    order, mirroring reference NodeStatus.order).

    `axes` sizes must multiply to the device count used.  Example:
      make_mesh({'dp': 2, 'tp': 4})  on 8 devices.
    """
    if devices is None:
        devices = jax.devices()
    names = tuple(axes.keys())
    sizes = tuple(int(s) for s in axes.values())
    n = int(np.prod(sizes))
    assert n <= len(devices), \
        f"mesh {axes} needs {n} devices, have {len(devices)}"
    dev_array = np.array(devices[:n]).reshape(sizes)
    return Mesh(dev_array, names)


def single_device_mesh():
    return make_mesh({"dp": 1})


class DistState:
    """Per-tensor layout: dim -> mesh axis (or tuple of axes).

    API parity with reference NodeStatus: ``splits`` plays the role of
    ``state`` (which dims are split and how), ``partial`` marks pending
    reductions over an axis, replication is implicit for unnamed axes
    (reference ``duplicate``).
    """

    def __init__(self, splits=None, partial=None):
        self.splits = dict(splits or {})   # {tensor_dim: axis or (axes...)}
        self.partial = partial             # mesh axis name or None

    def to_pspec(self, ndim=None):
        if not self.splits:
            return P()
        if ndim is not None and max(self.splits) >= ndim:
            raise ValueError(
                f"DistState splits {self.splits} reference dim "
                f">= tensor rank {ndim}")
        ndim = ndim if ndim is not None else (max(self.splits) + 1)
        spec = []
        for d in range(ndim):
            a = self.splits.get(d)
            spec.append(a if (a is None or isinstance(a, str)) else tuple(a))
        return P(*spec)

    def __repr__(self):
        return f"DistState(splits={self.splits}, partial={self.partial})"

    # -- reference NodeStatus-style helpers --------------------------------
    def combine(self, other):
        s = dict(self.splits)
        s.update(other.splits)
        return DistState(s, self.partial or other.partial)

    @staticmethod
    def replicated():
        return DistState()

    @staticmethod
    def shard(dim, axis):
        return DistState({dim: axis})


def to_named_sharding(mesh, state_or_spec, ndim=None):
    if isinstance(state_or_spec, DistState):
        spec = state_or_spec.to_pspec(ndim)
    elif isinstance(state_or_spec, P):
        spec = state_or_spec
    else:
        spec = P(*state_or_spec)
    return NamedSharding(mesh, spec)


def replicated(mesh):
    return NamedSharding(mesh, P())


class DeviceGroup:
    """Named device group (reference context.py:28).  On TPU a group is a
    slice of the mesh; kept for API parity and for pipeline stage
    assignment (raw_ctx annotations)."""

    def __init__(self, devices_or_stage):
        self.spec = devices_or_stage

    def __repr__(self):
        return f"DeviceGroup({self.spec})"
