"""HetPipe: pipeline parallelism + parameter-server weight sync.

Reference: pipedream_subexecutor.py:78-88 — with ``pipeline='hetpipe'`` each
worker replica runs the pipeline schedule locally, ACCUMULATES grads and
pushes them to the parameter server (server-side optimizer applies the
update, BSP/SSP-gated); with the preduce flavor, grads are instead averaged
over whichever worker replicas show up within the matchmaking window
(preduce.py, ps-lite preduce_handler.cc).

TPU mapping: the pipeline itself is the shard_map spmd pipeline
(parallel/pipeline.py) over a 'pp' mesh axis; the PS plane is the host-side
native store (ps/store.py).  Worker replicas on other TPU-VM hosts reach
the same store over DCN — in-process they are threads (launcher.launch_local),
which is also how the tests exercise the consistency protocols.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..ps.store import EmbeddingTable, SSPController
from ..ps.preduce import PReduceScheduler


class DenseParamStore:
    """PS-resident dense parameters (reference PSFunc DensePush/DDPushPull):
    one table per pytree leaf, one row per leading index, server-side
    optimizer applies pushed gradients."""

    def __init__(self, params, optimizer="sgd", lr=0.01, tables=None,
                 seed_values=True, **opt_kwargs):
        self.treedef = jax.tree_util.tree_structure(params)
        leaves = jax.tree_util.tree_leaves(params)
        self.shapes = [l.shape for l in leaves]
        arrs = [np.asarray(l, np.float32).reshape(l.shape[0], -1)
                if l.ndim > 1 else np.asarray(l, np.float32).reshape(1, -1)
                for l in leaves]
        if tables is None:
            tables = [EmbeddingTable(a.shape[0], a.shape[1],
                                     optimizer=optimizer, lr=lr,
                                     init_scale=0, **opt_kwargs)
                      for a in arrs]
        self.tables = tables
        if seed_values:
            for t, a in zip(self.tables, arrs):
                t.set_rows(np.arange(a.shape[0]), a)

    @classmethod
    def remote(cls, host, port, params, seed_values=False, **kw):
        """Leaves served by one PSServer process's named tables
        ('leaf0'..'leafN', ps/rpc.serve_dense_params).  Only one replica
        should seed_values; the rest attach (reference workers pull the
        server's authoritative weights)."""
        from ..ps.rpc import RemoteTable
        leaves = jax.tree_util.tree_leaves(params)
        tables = [RemoteTable(host, port, table=f"leaf{i}", **kw)
                  for i in range(len(leaves))]
        return cls(params, tables=tables, seed_values=seed_values)

    def _rows(self, leaf_idx):
        return np.arange(self.tables[leaf_idx].rows)

    def push_grads(self, grads):
        for i, g in enumerate(jax.tree_util.tree_leaves(grads)):
            g = np.asarray(g, np.float32)
            g = g.reshape(g.shape[0], -1) if g.ndim > 1 else g.reshape(1, -1)
            self.tables[i].push(self._rows(i), g)

    def pull(self):
        leaves = []
        for i, shape in enumerate(self.shapes):
            arr = self.tables[i].lookup(self._rows(i)).reshape(shape)
            leaves.append(jnp.asarray(arr))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class _ThreadReducer:
    """In-process grad averaging for preduce groups (the thread analogue of
    the lazily-built NCCL subgroups; real multi-host replicas average over
    the dp mesh axis with ps.preduce.masked_mean_allreduce instead)."""

    def __init__(self):
        self._lock = threading.Condition()
        self._rounds = {}

    def reduce(self, round_id, rank, partner, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        tree = jax.tree_util.tree_structure(grads)
        # key by (round, group): matchmaking can split one round into
        # disjoint groups (a straggler missing the window forms its own),
        # and the groups must not share a slot
        key = (round_id, tuple(partner))
        with self._lock:
            slot = self._rounds.setdefault(key, {"reads": 0})
            slot[rank] = [np.asarray(l, np.float32) for l in leaves]
            self._lock.notify_all()
            while not all(r in slot for r in partner):
                self._lock.wait()
            acc = [np.mean([slot[r][i] for r in partner], axis=0)
                   for i in range(len(leaves))]
            slot["reads"] += 1
            if slot["reads"] == len(partner):
                del self._rounds[key]
        return jax.tree_util.tree_unflatten(
            tree, [jnp.asarray(a) for a in acc])


class HetPipeTrainer:
    """Drives one worker replica's pipeline + weight synchronization.

    mode='hetpipe': grads pushed to the PS (server-side optimizer), fresh
    weights pulled back, SSP clocks bound the fastest-slowest spread
    (reference executor.py:226 + _compute_ssp; staleness=0 is BSP).
    mode='preduce': grads averaged over the workers that arrive within
    ``wait_time`` ms, then applied locally (straggler mitigation).
    """

    def __init__(self, pipeline, init_params, nworkers, mode="hetpipe",
                 optimizer="sgd", lr=0.01, staleness=1, wait_time=100.0,
                 scheduler=None, ssp_timeout=120.0, store=None, ssp=None,
                 reducer=None, **opt_kwargs):
        assert mode in ("hetpipe", "preduce")
        self.pipeline = pipeline
        self.nworkers = nworkers
        self.mode = mode
        self.lr = lr
        self.wait_time = wait_time
        self.ssp_timeout = ssp_timeout
        # jit once: pipeline.grads builds fresh shard_map closures per call,
        # so an unjitted loop would retrace + recompile every step
        self._grads = jax.jit(pipeline.grads)
        # store/ssp/scheduler/reducer injection: pass the DCN clients
        # (ps/rpc DenseParamStore.remote + RemoteCoordinator) to run
        # replicas as separate PROCESSES against one server authority;
        # the in-process defaults are the thread-replica test harness
        if mode == "hetpipe":
            self.store = store or DenseParamStore(
                init_params, optimizer=optimizer, lr=lr, **opt_kwargs)
            self.ssp = ssp or SSPController(nworkers, staleness=staleness)
        else:
            if optimizer != "sgd" or opt_kwargs:
                raise ValueError(
                    "mode='preduce' applies a LOCAL sgd step after the "
                    "group average; server-side optimizers only exist in "
                    "mode='hetpipe'")
            self.scheduler = scheduler or PReduceScheduler(nworkers)
            self.reducer = reducer or _ThreadReducer()
        self._round = [0] * nworkers
        # workers that finished or died: excluded from the SSP min so the
        # survivors don't spin forever on a frozen clock
        self._inactive = set()

    def mark_done(self, rank):
        """Call when a worker finishes (or from an except block when it
        dies) so SSP-gated peers stop waiting on its clock."""
        self._inactive.add(rank)

    def _clocks(self):
        if hasattr(self.ssp, "clocks"):
            return self.ssp.clocks()        # one RPC for all clocks
        return [self.ssp.clock(w) for w in range(self.nworkers)]

    def _ssp_can_advance(self, rank):
        active = [w for w in range(self.nworkers)
                  if w not in self._inactive]
        if not active:
            return True
        cl = self._clocks()
        lo = min(cl[w] for w in active)
        return cl[rank] - lo <= self.ssp.staleness

    def step(self, rank, params, xs, targets):
        """One training round for worker ``rank``; returns (loss, params)."""
        try:
            loss, grads = self._grads(params, xs, targets)
        except Exception:
            self.mark_done(rank)   # unblock SSP-gated peers
            raise
        if self.mode == "hetpipe":
            self.store.push_grads(grads)
            self.ssp.tick(rank)
            # SSP gate: block while more than `staleness` ahead of the
            # slowest ACTIVE worker (reference psf/ssp.h kSSPSync), with a
            # deadline so a silently-dead peer surfaces as an error
            deadline = time.monotonic() + self.ssp_timeout
            while not self._ssp_can_advance(rank):
                if time.monotonic() > deadline:
                    self.mark_done(rank)
                    raise RuntimeError(
                        f"SSP wait exceeded {self.ssp_timeout}s: a peer "
                        f"stopped ticking (clocks="
                        f"{self._clocks()}"
                        f"); call mark_done(rank) for finished workers")
                # remote clocks poll over RPC: back off harder than the
                # in-process 1ms spin
                time.sleep(0.01 if hasattr(self.ssp, "clocks") else 0.001)
            new_params = self.store.pull()
        else:
            rid = self._round[rank]
            self._round[rank] += 1
            partner = self.scheduler.get_partner(
                rid, rank, self.nworkers, self.wait_time)
            self.last_partner = partner
            mean_g = self.reducer.reduce(rid, rank, partner, grads)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - self.lr * g, params, mean_g)
        return float(loss), new_params
