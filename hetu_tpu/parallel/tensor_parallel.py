"""Explicit Megatron-style tensor-parallel blocks (shard_map).

Reference: tools/Hetu-Galvatron/galvatron/core/tensor_parallel/
transformer.py and the vendored megatron/core/tensor_parallel/layers.py —
column/row-parallel linear with hand-placed f/g collectives,
VocabParallelEmbedding (rows split over tp ranks, out-of-range ids masked
to 0 then all-reduced) and vocab_parallel_cross_entropy (per-rank partial
logits reduced with max/sum psums so the full [T, V] logits never
materialize on one device).

Most TP in this framework is GSPMD-driven (annotate shardings, let XLA
insert collectives — parallel/strategies.py MegatronLM).  This module is
the explicit-control path for the two places where the hand-written
pattern beats compiler propagation:

  * the LM head + cross-entropy, where keeping logits vocab-sharded
    through the reduction is a memory guarantee, not a heuristic;
  * benchmark kernels where collective placement must be exact.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..platform import shard_map
from jax.sharding import PartitionSpec as P


def _axis_size(axis):
    return lax.psum(1, axis)


def vocab_range(vocab_size, axis):
    """This shard's [start, end) slice of the vocabulary."""
    size = _axis_size(axis)
    per = vocab_size // size
    start = lax.axis_index(axis) * per
    return start, start + per


def vocab_parallel_embedding(local_table, ids, vocab_size, axis="tp"):
    """Lookup from a vocab-sharded [V/tp, H] table (inside shard_map).

    Out-of-range ids hit a zero row locally; the psum sums the one shard
    that owns each id (reference VocabParallelEmbedding.forward: mask,
    local lookup, all-reduce).
    """
    start, end = vocab_range(vocab_size, axis)
    mine = (ids >= start) & (ids < end)
    local = jnp.where(mine, ids - start, 0)
    rows = jnp.take(local_table, local, axis=0)
    rows = jnp.where(mine[..., None], rows, 0.0)
    return lax.psum(rows, axis)


def vocab_parallel_cross_entropy(local_logits, labels, vocab_size,
                                 axis="tp", ignored_index=-1):
    """Sparse softmax-CE over vocab-sharded logits (inside shard_map).

    local_logits: [T, V/tp] this shard's slice; labels: [T] global ids.
    Never materializes [T, V]: max and sum-exp reduce with psums, and the
    correct-label logit comes from the owning shard only (reference
    megatron _VocabParallelCrossEntropy.forward).
    """
    x = local_logits.astype(jnp.float32)
    # the max is a numerical-stability shift whose gradient cancels in
    # (m + log z) - picked; stop_gradient also sidesteps pmax's missing
    # differentiation rule
    # stop_gradient BEFORE pmax: with a symbolically-zero tangent the
    # missing pmax differentiation rule is never consulted
    m = lax.pmax(jnp.max(lax.stop_gradient(x), axis=-1), axis)  # [T]
    z = lax.psum(jnp.sum(jnp.exp(x - m[:, None]), axis=-1), axis)
    start, end = vocab_range(vocab_size, axis)
    lab = jnp.maximum(labels.astype(jnp.int32), 0)
    mine = (lab >= start) & (lab < end)
    local = jnp.where(mine, lab - start, 0)
    picked = jnp.take_along_axis(x, local[:, None], axis=-1)[:, 0]
    picked = lax.psum(jnp.where(mine, picked, 0.0), axis)
    loss = (m + jnp.log(z)) - picked
    return jnp.where(labels == ignored_index, 0.0, loss)


def column_parallel_linear(x, w_local, b_local=None, axis="tp",
                           gather_output=False):
    """y_local = x @ w_local (+ b_local); w sharded on the OUTPUT dim.
    The identity-forward/psum-backward 'f' function is what autodiff of
    the replicated input gives for free under shard_map."""
    y = x @ w_local
    if b_local is not None:
        y = y + b_local
    if gather_output:
        y = lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear(x_local, w_local, b=None, axis="tp"):
    """y = psum(x_local @ w_local) (+ b); w sharded on the INPUT dim —
    the 'g' all-reduce the reference places after row-parallel matmuls."""
    y = lax.psum(x_local @ w_local, axis)
    if b is not None:
        y = y + b
    return y


def shard_vocab_table(mesh, table, axis="tp"):
    """[V, H] -> placed vocab-sharded over ``axis``."""
    from jax.sharding import NamedSharding
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def tp_lm_head_loss(mesh, hidden, table, labels, axis="tp",
                    ignored_index=-1, dp_axis=None):
    """Tied-head LM loss with the full vocab-parallel treatment.

    hidden: [T, H] (replicated over tp; optionally dp-sharded on dim 0),
    table: [V, H] vocab-sharded over ``axis``; labels: [T].
    Computes mean CE without ever materializing [T, V] logits on one
    device.  This is the memory contract MegatronLM's sharded LM head
    exists for (reference core/tensor_parallel/transformer.py LM head +
    vocab CE).
    """
    V = table.shape[0]
    in_hidden = P(dp_axis, None) if dp_axis else P()
    in_labels = P(dp_axis) if dp_axis else P()

    def body(h, tab, lab):
        logits_local = h @ tab.T                      # [T, V/tp]
        ce = vocab_parallel_cross_entropy(logits_local, lab, V, axis,
                                          ignored_index)
        n = lax.psum(jnp.sum((lab != ignored_index).astype(jnp.float32)),
                     dp_axis) if dp_axis else \
            jnp.sum((lab != ignored_index).astype(jnp.float32))
        s = lax.psum(jnp.sum(ce), dp_axis) if dp_axis else jnp.sum(ce)
        return s / jnp.maximum(n, 1.0)

    f = shard_map(body, mesh=mesh,
                  in_specs=(in_hidden, P(axis, None), in_labels),
                  out_specs=P())
    return f(hidden, table, labels)
