"""dispatch: per-node sharding annotations in the graph.

Reference: /root/reference/python/hetu/gpu_ops/Dispatch.py — `ht.dispatch`
placeholder ops mark TP split points; context.py's rewrite pass consumes
them and emits comm ops.  TPU redesign: a DispatchOp lowers to
``with_sharding_constraint`` inside the traced program, and GSPMD emits the
collectives — same user-facing contract, compiler-backed lowering.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding

from ..graph.node import Op
from .mesh import DistState


class DispatchOp(Op):
    def __init__(self, node, state, name=None):
        super().__init__(node, name=name or f"dispatch_{node.name}")
        if not isinstance(state, DistState):
            state = DistState(state)
        self.state = state

    def _compute(self, input_vals, ctx):
        (x,) = input_vals
        if ctx.mesh is None:
            return x
        sh = NamedSharding(ctx.mesh, self.state.to_pspec(x.ndim))
        return jax.lax.with_sharding_constraint(x, sh)


def dispatch(node, splits=None, partial=None, name=None):
    """Annotate/reshard a node (reference ht.dispatch).

    ``splits``: {tensor_dim: mesh_axis}.  Also records ``dist_state`` on the
    produced node so strategies/executors can read it back.
    """
    op = DispatchOp(node, DistState(splits, partial), name=name)
    op.dist_state = op.state
    return op
