from .mesh import (Mesh, DistState, DeviceGroup, make_mesh,
                   single_device_mesh, to_named_sharding, replicated)
from .dispatch import dispatch, DispatchOp
from .strategies import (Strategy, DataParallel, FSDP, MegatronLM,
                         ModelParallel4CNN)
from .pipeline import PipelineParallel, spmd_pipeline
from .hetpipe import HetPipeTrainer, DenseParamStore
from .context_parallel import (ring_attention, ulysses_attention,
                               ring_attention_shard, ulysses_attention_shard)
from . import collectives
from . import debug
from .search import (OptCNNSearch, FlexFlowSearch, GPipeSearch,
                     PipeDreamSearch, PipeOptSearch, SearchedStrategy,
                     partition_stages)
