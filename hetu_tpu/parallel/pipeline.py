"""Pipeline parallelism over a 'pp' mesh axis.

Reference: /root/reference/python/hetu/gpu_ops/{gpipe,pipedream}_subexecutor.py
— GPipe (all-forward-then-all-backward with micro-batch arr maps) and
PipeDream-1F1B with weight stashing, driven by per-rank Python schedulers
exchanging NCCL P2P messages (PipelineSend/Receive ops, shape handshakes).

TPU redesign: the whole pipeline is ONE SPMD program.  Stages are identical
sub-programs whose parameters carry a leading [pp] dim sharded on the 'pp'
mesh axis; micro-batches rotate between neighbor stages with
`lax.ppermute` inside a `lax.scan` over clock ticks (bubble included).
Differentiating the scanned forward gives the reverse schedule for free —
semantically the GPipe flush schedule (grads accumulated over micro-batches,
single optimizer step), with `jax.checkpoint` on the stage body as the
activation-memory knob (the reference's weight-stashing exists to tolerate
async staleness, which a flush schedule does not incur).  The 1F1B
"pipedream_flush" memory profile comes from `schedule='interleaved'`, which
scans micro-batches with immediate backward via jax.vjp inside the loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..platform import shard_map

from .collectives import varying


def _varying(x, axes=("pp",)):
    return varying(x, axes)


def spmd_pipeline(stage_fn, n_stages, n_micro, *, remat=True):
    """Build the per-shard pipeline body (call inside shard_map over 'pp').

    stage_fn(stage_params, x) -> y : one stage applied to one micro-batch.
    Inputs xs: [n_micro, mb, ...] (replicated across pp); returns
    [n_micro, mb, ...] outputs of the LAST stage (valid on every shard).
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def body(params, xs):
        # shard of [n_stages, ...]-stacked params has leading dim 1
        params = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), params)
        stage = lax.axis_index("pp")
        n_ticks = n_micro + n_stages - 1
        mb_shape = xs.shape[1:]
        # initial carries must be marked device-varying over 'pp' (they
        # become varying after the first ppermute / stage-dependent update)
        state = _varying(jnp.zeros(mb_shape, xs.dtype))
        outs = _varying(jnp.zeros_like(xs))

        def tick(carry, t):
            state, outs = carry
            # stage 0 injects micro-batch t (zeros past the last one)
            inject = lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
            x_in = jnp.where(stage == 0, inject, state)
            y = stage_fn(params, x_in)
            # last stage emits micro-batch t - (n_stages-1)
            out_idx = t - (n_stages - 1)
            emit = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            idx = jnp.maximum(out_idx, 0)
            cur = lax.dynamic_index_in_dim(outs, idx, axis=0, keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(emit, y, cur), idx, axis=0)
            # rotate activations to the next stage (ring; the wraparound
            # value into stage 0 is ignored by the injection mux)
            nxt = lax.ppermute(
                y, "pp", [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outs), None

        (state, outs), _ = lax.scan(tick, (state, outs),
                                    jnp.arange(n_ticks))
        # every shard returns the last stage's outputs (broadcast over pp)
        mask = (stage == n_stages - 1).astype(xs.dtype)
        return lax.psum(outs * mask, "pp")

    return body


class PipelineParallel:
    """Host-level wrapper: pipelined loss/train over a mesh with a 'pp' axis.

    ``stage_fn(stage_params, x) -> x'`` is the repeated stage;
    ``loss_fn(last_out, targets) -> scalar`` closes the graph (computed
    replicated after the pipeline).  ``loss_fn`` MUST reduce by MEAN over
    the leading micro-batch dimension it is given (any mean-style loss):
    'interleaved' evaluates it per micro-batch and averages, so a sum-style
    reduction would disagree with 'gpipe' by a factor of n_micro.
    ``schedule``: 'gpipe' (scan + grad, all activations stashed unless
    remat) — the reference's SubExecutor4Gpipe; 'interleaved' computes
    fwd+bwd per micro-batch (1F1B-flush memory profile; reference
    SubExecutor4Pipedream with pipedream_flush semantics).
    """

    def __init__(self, mesh, stage_fn, n_stages, n_micro, loss_fn,
                 schedule="gpipe", remat=True):
        assert "pp" in mesh.axis_names
        assert mesh.shape["pp"] == n_stages
        self.mesh = mesh
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.loss_fn = loss_fn
        self.schedule = schedule
        self.stage_fn = stage_fn
        self.remat = remat

    def _specs(self, params):
        # stage-stacked params: leading dim = pp
        return jax.tree_util.tree_map(lambda _: P("pp"), params)

    def loss(self, params, xs, targets):
        """xs: [n_micro, mb, ...]; targets: [n_micro, mb, ...]."""
        pipe = spmd_pipeline(self.stage_fn, self.n_stages, self.n_micro,
                             remat=self.remat)

        def shard_body(params, xs, targets):
            outs = pipe(params, xs)
            return self.loss_fn(outs, targets)

        f = shard_map(shard_body, mesh=self.mesh,
                      in_specs=(self._specs(params), P(), P()),
                      out_specs=P())
        return f(params, xs, targets)

    def grads(self, params, xs, targets):
        if self.schedule == "interleaved":
            return self._grads_1f1b(params, xs, targets)
        loss, g = jax.value_and_grad(self.loss)(params, xs, targets)
        return loss, g

    def _grads_1f1b(self, params, xs, targets):
        """Per-micro-batch fwd+bwd accumulation (pipedream-flush memory:
        at most one micro-batch of activations live per stage)."""
        pipe = spmd_pipeline(self.stage_fn, self.n_stages, 1,
                             remat=self.remat)

        def shard_body(params, xs, targets):
            def one_micro(carry, xt):
                acc, lsum = carry
                x, t = xt

                def mloss(p):
                    outs = pipe(p, x[None])
                    return self.loss_fn(outs, t[None])

                l, g = jax.value_and_grad(mloss)(params)
                acc = jax.tree_util.tree_map(jnp.add, acc, g)
                return (acc, lsum + l), None

            zero = jax.tree_util.tree_map(jnp.zeros_like, params)
            (g, lsum), _ = lax.scan(one_micro, (zero, 0.0), (xs, targets))
            n = xs.shape[0]
            return lsum / n, jax.tree_util.tree_map(lambda a: a / n, g)

        f = shard_map(shard_body, mesh=self.mesh,
                      in_specs=(self._specs(params), P(), P()),
                      out_specs=(P(), self._specs(params)))
        return f(params, xs, targets)
