"""Parallelization strategies: annotate a graph with shardings.

Reference: /root/reference/python/hetu/distributed_strategies/simple.py —
`DataParallel` (:6), `ModelParallel4CNN` (:46), `ModelParallel4LM` (:113),
`OneWeirdTrick4CNN` (:119), `MegatronLM` (:174); each assigns raw_ctx +
NodeStatus to every node.  Here a Strategy assigns `dist_state` (mesh-axis
layouts) to placeholders/variables; the executor turns them into jit
in_shardings and GSPMD propagates through the program — replacing the
reference's fixed-point NodeStatus inference (context.py:1008-1468) with the
compiler's propagation pass.
"""

from __future__ import annotations

import re

from ..graph.node import PlaceholderOp, VariableOp, find_topo_sort
from .mesh import DistState, make_mesh


class Strategy:
    """Base (reference distributed_strategies/base.py:13)."""

    mesh = None

    def annotate(self, eval_nodes):
        raise NotImplementedError

    # reference API name
    def set_raw_ctxs_n_states(self, eval_nodes):
        return self.annotate(eval_nodes)

    # -- config persistence (reference Strategy.save_json base.py:183) ----
    def config(self):
        """JSON-able constructor config (mesh stored as axis sizes).

        Raises for strategies carrying non-scalar state (e.g. a searched
        per-node assignment) — those need their own serializers rather
        than silent data loss.
        """
        out = {"strategy": type(self).__name__}
        for k, v in vars(self).items():
            if k == "mesh":
                out["mesh_axes"] = (dict(v.shape) if v is not None
                                    else None)
            elif isinstance(v, (int, float, str, bool, type(None))):
                out[k] = v
            else:
                raise TypeError(
                    f"{type(self).__name__}.{k} ({type(v).__name__}) is "
                    f"not JSON-persistable; this strategy needs a custom "
                    f"serializer")
        return out

    def save_json(self, path):
        import json
        with open(path, "w") as f:
            json.dump(self.config(), f, indent=2)

    @staticmethod
    def load_json(path):
        """Rebuild a strategy from a saved config (simple strategies)."""
        import json
        from . import strategies as S
        with open(path) as f:
            cfg = json.load(f)
        name = cfg.pop("strategy")
        cls = getattr(S, name, None)
        if cls is None or not (isinstance(cls, type)
                               and issubclass(cls, Strategy)):
            raise ValueError(f"{name!r} is not a Strategy in "
                             f"parallel.strategies")
        mesh_axes = cfg.pop("mesh_axes", None)
        if mesh_axes:
            cfg["mesh"] = make_mesh(mesh_axes)
        return cls(**cfg)


class DataParallel(Strategy):
    """Batch-dim sharding over a 'dp' axis (reference simple.py:6).

    Gradient all-reduce is implicit: batch-sharded loss + replicated params
    make XLA insert the reduction the reference expressed as
    AllReduceCommunicateOp on every grad edge (executor.py:278-283).
    """

    def __init__(self, mesh=None, ndev=None, axis="dp",
                 shard_batch_dim=0):
        self.mesh = mesh if mesh is not None else make_mesh(
            {axis: ndev or _ndev()})
        self.axis = axis
        self.shard_batch_dim = shard_batch_dim

    def annotate(self, eval_nodes):
        for n in find_topo_sort(eval_nodes):
            if isinstance(n, PlaceholderOp):
                n.dist_state = DistState({self.shard_batch_dim: self.axis})
        return self.mesh


class FSDP(Strategy):
    """ZeRO-3-style parameter sharding along the dp axis (Galvatron's
    dp_type='fsdp', tools/Hetu-Galvatron/galvatron/core/parallel.py:166).
    Params/optimizer state shard on dim 0; XLA all-gathers at use and
    reduce-scatters grads."""

    def __init__(self, mesh=None, ndev=None, axis="dp", min_size=1024):
        self.mesh = mesh if mesh is not None else make_mesh(
            {axis: ndev or _ndev()})
        self.axis = axis
        self.min_size = min_size

    def annotate(self, eval_nodes):
        import numpy as np
        size = self.mesh.shape[self.axis]
        for n in find_topo_sort(eval_nodes):
            if isinstance(n, PlaceholderOp):
                n.dist_state = DistState({0: self.axis})
            elif isinstance(n, VariableOp) and n.trainable:
                if (int(np.prod(n.shape)) >= self.min_size
                        and n.shape and n.shape[0] % size == 0):
                    n.dist_state = DistState({0: self.axis})
        return self.mesh


class MegatronLM(Strategy):
    """2D dp×tp for transformer stacks (reference simple.py:174).

    Column-parallel: QKV projections and FFN up-projection (output dim on
    'tp'); row-parallel: attention output and FFN down-projection (input dim
    on 'tp').  Name patterns follow the layer library's naming contract
    (layers/attention.py, layers/transformer.py).  GSPMD inserts the psum
    pairs the reference placed as AllReduce after row-parallel matmuls.
    """

    COL_W = re.compile(r"(_q|_k|_v|_in|_gate|_up)_weight$")
    COL_B = re.compile(r"(_q|_k|_v|_in|_gate|_up)_bias$")
    ROW_W = re.compile(r"_out_weight$")
    # embedding tables (layers/common.py Embedding -> '<name>_table'):
    # vocab-parallel dim-0 sharding; a table also used as a tied LM head
    # (h @ table^T) then yields vocab-sharded logits, and the sparse CE's
    # reductions stay sharded under GSPMD.  Reference: megatron
    # VocabParallelEmbedding + sharded LM head
    # (core/tensor_parallel/transformer.py).
    EMB_W = re.compile(r"_table$")

    def __init__(self, mesh=None, dp=1, tp=None, dp_axis="dp",
                 tp_axis="tp", shard_embeddings=True):
        if mesh is None:
            tp = tp or (_ndev() // dp)
            mesh = make_mesh({dp_axis: dp, tp_axis: tp})
        self.mesh = mesh
        self.dp_axis, self.tp_axis = dp_axis, tp_axis
        self.shard_embeddings = shard_embeddings

    def annotate(self, eval_nodes):
        tp_size = self.mesh.shape[self.tp_axis]
        matched = 0
        skipped = []
        for n in find_topo_sort(eval_nodes):
            if isinstance(n, PlaceholderOp):
                n.dist_state = DistState({0: self.dp_axis})
            elif isinstance(n, VariableOp):
                if self.COL_W.search(n.name) and n.shape[1] % tp_size == 0:
                    n.dist_state = DistState({1: self.tp_axis})
                elif self.COL_B.search(n.name) and n.shape[0] % tp_size == 0:
                    n.dist_state = DistState({0: self.tp_axis})
                elif self.ROW_W.search(n.name) and n.shape[0] % tp_size == 0:
                    n.dist_state = DistState({0: self.tp_axis})
                elif (self.shard_embeddings and self.EMB_W.search(n.name)
                      and n.shape[0] % tp_size == 0):
                    n.dist_state = DistState({0: self.tp_axis})
                else:
                    if (self.COL_W.search(n.name)
                            or self.COL_B.search(n.name)
                            or self.ROW_W.search(n.name)
                            or (self.shard_embeddings
                                and self.EMB_W.search(n.name))):
                        skipped.append(n.name)  # matched name, bad divisor
                    continue
                matched += 1
        if tp_size > 1 and matched == 0:
            # the naming contract silently matching NOTHING means every
            # parameter stays replicated — plain DP at tp memory cost
            import warnings
            warnings.warn(
                "MegatronLM.annotate: no variable matched the naming "
                "contract (_q/_k/_v/_in/_out weights, *_table embeddings)"
                + (f"; name-matched but not divisible by tp={tp_size}: "
                   f"{skipped}" if skipped else "")
                + " — all parameters remain replicated. Check layer "
                "names or pass shard rules explicitly.",
                stacklevel=2)
        self.matched_variables = matched
        return self.mesh


class ModelParallel4CNN(Strategy):
    """TP for the classifier head of CNNs (reference simple.py:46/119 —
    'one weird trick': conv layers data-parallel, FC layers model-parallel)."""

    def __init__(self, mesh=None, dp=1, tp=None):
        if mesh is None:
            tp = tp or (_ndev() // dp)
            mesh = make_mesh({"dp": dp, "tp": tp})
        self.mesh = mesh

    def annotate(self, eval_nodes):
        tp_size = self.mesh.shape["tp"]
        for n in find_topo_sort(eval_nodes):
            if isinstance(n, PlaceholderOp):
                n.dist_state = DistState({0: "dp"})
            elif isinstance(n, VariableOp):
                if (n.name.endswith("_fc_weight")
                        and n.shape[1] % tp_size == 0):
                    n.dist_state = DistState({1: "tp"})
        return self.mesh


class PlannedParallel(Strategy):
    """A planner-emitted plan artifact as a graph annotation.

    The auto-parallel planner (``hetu_tpu/planner``) emits a searched
    ``hetu_train_plan`` dict; this strategy lowers it onto a flat node
    graph by delegating to the simple strategy the plan's per-layer
    assignment implies: searched tp > 1 -> :class:`MegatronLM` on a
    dp×tp mesh, fsdp-majority dp_types -> :class:`FSDP`, else
    :class:`DataParallel`.  (Pipeline stages are a runtime-level
    concept — ``galvatron/runtime.HybridParallelModel`` executes them —
    so a node-graph annotation uses the plan's intra-stage layout.)

    ``config()``/``save_json`` persist the full plan dict, so a saved
    strategy round-trips through :meth:`Strategy.load_json`."""

    def __init__(self, plan, mesh_shape=None, devices=None):
        cfg = plan["config"] if "config" in plan else plan
        from ..galvatron.config import HybridParallelConfig
        hp = (HybridParallelConfig.from_json(cfg)
              if isinstance(cfg, dict) else cfg)
        self.plan = dict(plan)
        self.mesh_shape = dict(mesh_shape) if mesh_shape else None
        # devices: the concrete device pool to build the mesh over —
        # the elastic trainer's surviving set after a chip loss.
        # Default (None) is jax.devices(), the full fleet.
        self._devices = list(devices) if devices is not None else None
        tp = max(int(t) for t in hp.tp_sizes)
        world = int(hp.world or hp.pp_deg * tp)
        dp = max(1, world // (int(hp.pp_deg) * tp))
        fsdp = sum(int(t) for t in hp.dp_types) * 2 > len(hp.dp_types)
        self.tp, self.dp = tp, dp
        mesh = (make_mesh(self.mesh_shape, devices=self._devices)
                if self.mesh_shape else None)
        if tp > 1:
            self._inner = MegatronLM(
                mesh=mesh if mesh is not None
                else make_mesh({"dp": dp, "tp": tp},
                               devices=self._devices))
        elif fsdp and dp > 1:
            self._inner = FSDP(
                mesh=mesh if mesh is not None
                else make_mesh({"dp": dp}, devices=self._devices))
        else:
            self._inner = DataParallel(
                mesh=mesh if mesh is not None
                else make_mesh({"dp": dp}, devices=self._devices))
        self.lowered = type(self._inner).__name__

    def annotate(self, eval_nodes):
        self.mesh = self._inner.annotate(eval_nodes)
        return self.mesh

    def config(self):
        return {"strategy": type(self).__name__,
                "plan": self.plan,
                "mesh_shape": self.mesh_shape}


def _ndev():
    import jax
    return len(jax.devices())
