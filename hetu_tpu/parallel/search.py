"""Auto-parallel strategy search (reference: python/hetu/
distributed_strategies/ — `BaseSearchingStrategy` backbone grouping
(base.py:47-141), `FlexFlow` MCMC (flexflow.py:12), `OptCNN` dynamic
programming (optcnn.py:9), `GPipe`/`PipeDream`/`PipeOpt` stage partition
searches (gpipe.py:6, pipedream.py:7, pipeopt.py:9)).

TPU redesign: the search space is per-backbone-node layout choices over a
named mesh (dp batch split × tp weight split) instead of raw device
placements — GSPMD realizes whatever the search picks, so the searcher only
scores (compute shard time + reshard collectives) with the HetuSimulator and
emits Strategy annotations (variable/placeholder DistStates).  Pipeline
searchers partition profiled per-layer costs into stages for the
PipelineParallel runtime.
"""

from __future__ import annotations

import math

import numpy as np

from ..graph.node import PlaceholderOp, VariableOp, find_topo_sort
from ..profiler import (HetuSimulator, shape_map,
                        tensor_bytes, op_kind)
from .mesh import DistState, make_mesh
from .strategies import Strategy

_BACKBONE_TYPES = ("matmul", "linear", "conv", "attention", "batchmatmul")


def backbone_nodes(eval_nodes):
    """FLOP-carrying nodes the search decides layouts for; every other node
    follows its producer (reference backbone grouping base.py:47-141)."""
    out = []
    for n in find_topo_sort(eval_nodes):
        tname = op_kind(n)
        if any(t in tname for t in _BACKBONE_TYPES):
            out.append(n)
    return out


class LayoutChoice:
    """One candidate layout for a backbone node: how much of the batch axis
    and of the weight's output dim are split."""

    def __init__(self, dp=1, tp=1, tp_dim=None):
        self.dp, self.tp, self.tp_dim = dp, tp, tp_dim

    @property
    def shard_factor(self):
        return self.dp * self.tp

    def __repr__(self):
        return f"LayoutChoice(dp={self.dp}, tp={self.tp})"

    def __eq__(self, other):
        return (self.dp, self.tp, self.tp_dim) == \
            (other.dp, other.tp, other.tp_dim)

    def __hash__(self):
        return hash((self.dp, self.tp, self.tp_dim))


def _weight_of(node):
    for i in node.inputs:
        if isinstance(i, VariableOp) and len(i.shape) >= 2:
            return i
    return None


def candidate_choices(node, shapes, ndev):
    """Feasible (dp, tp) splits for one backbone node on ndev devices."""
    out_struct = shapes.get(node)
    w = _weight_of(node)
    cands = [LayoutChoice(1, 1)]
    if out_struct is None:
        return cands
    batch = out_struct.shape[0] if out_struct.shape else 1
    d = 2
    while d <= ndev:
        if batch % d == 0:
            cands.append(LayoutChoice(dp=d))
            if w is not None:
                t = 2
                while d * t <= ndev:
                    if w.shape[-1] % t == 0:
                        cands.append(LayoutChoice(dp=d, tp=t, tp_dim=1))
                    t *= 2
        d *= 2
    if w is not None:
        t = 2
        while t <= ndev:
            if w.shape[-1] % t == 0:
                cands.append(LayoutChoice(dp=1, tp=t, tp_dim=1))
            t *= 2
    return cands


class GraphCost:
    """Scores an assignment {backbone_node: LayoutChoice}.

    With ``mem_budget_bytes`` set, assignments whose simulated PER-DEVICE
    memory (parameters × replication × optimizer-slot multiplier +
    sharded activations) exceed the budget score infinite — the search
    REJECTS them instead of ranking them (reference: FlexFlow simulates
    memory and tests feasibility, flexflow.py:12 + memory_pool.py:147
    ``test_memory``; VERDICT r3 item 4)."""

    def __init__(self, eval_nodes, ndev, simulator=None, feed_shapes=None,
                 mem_budget_bytes=None, opt_slots_mult=3.0):
        self.eval_nodes = list(eval_nodes)
        self.ndev = ndev
        self.sim = simulator or HetuSimulator()
        self.shapes = shape_map(self.eval_nodes, feed_shapes)
        self.backbone = backbone_nodes(self.eval_nodes)
        self.mem_budget_bytes = mem_budget_bytes
        # params + grad/optimizer state (Adam: p + m + v); SGD callers can
        # pass 1.0
        self.opt_slots_mult = opt_slots_mult
        bb = set(self.backbone)
        self._rest = [n for n in find_topo_sort(self.eval_nodes)
                      if n not in bb
                      and not isinstance(n, (PlaceholderOp, VariableOp))]
        self._rest_time = {}  # dp degree -> summed non-backbone time
        self._all_vars = [n for n in find_topo_sort(self.eval_nodes)
                          if isinstance(n, VariableOp) and n.trainable]

    @staticmethod
    def _var_bytes(v):
        n = 1
        for d in v.shape:
            n *= int(d)
        return n * np.dtype(v.dtype).itemsize

    def memory_bytes(self, assignment):
        """Simulated per-device bytes: each backbone weight divided by
        its tp split (dp REPLICATES weights — the memory dp costs and tp
        saves), every other trainable replicated, plus live activations
        at each node's shard factor (the backward keeps them)."""
        total = 0.0
        sharded = {}
        for node in self.backbone:
            c = assignment.get(node, LayoutChoice())
            w = _weight_of(node)
            if w is not None:
                sharded[w] = max(sharded.get(w, 1), c.tp)
            out = self.shapes.get(node)
            if out is not None:
                total += tensor_bytes(out) / c.shard_factor
        for v in self._all_vars:
            total += (self._var_bytes(v) * self.opt_slots_mult
                      / sharded.get(v, 1))
        dp = max((c.dp for c in assignment.values()), default=1)
        for n in self._rest:
            total += tensor_bytes(self.shapes.get(n)) / dp
        return total

    def feasible(self, assignment):
        return (self.mem_budget_bytes is None
                or self.memory_bytes(assignment) <= self.mem_budget_bytes)

    def maybe_record(self, measure, feed_shapes=None):
        """Profile each distinct op once into the simulator's cache (the
        reference's profiling-backed simulate, base.py:663); roofline
        estimates back-fill anything that fails to profile."""
        if not measure:
            return
        try:
            self.sim.record(self.eval_nodes, feed_shapes)
        except Exception:
            pass

    def node_cost(self, node, choice):
        t = self.sim.op_time(node, self.shapes,
                             shard_factor=choice.shard_factor)
        # tp matmuls leave partial sums → allreduce of the sharded output
        if choice.tp > 1:
            nbytes = tensor_bytes(self.shapes.get(node)) / choice.shard_factor
            t += self.sim.collective_time(nbytes, choice.tp, "all_reduce")
        return t

    def transition_cost(self, prev_choice, choice, node):
        """Reshard between consecutive backbone layouts (activation
        all-gather when the split pattern changes — reference
        cross_send/cross_receive context.py:1658)."""
        if prev_choice == choice:
            return 0.0
        nbytes = tensor_bytes(self.shapes.get(node))
        moved = max(prev_choice.shard_factor, choice.shard_factor)
        return self.sim.collective_time(nbytes / moved, moved, "all_gather")

    def total(self, assignment):
        if not self.feasible(assignment):
            return float("inf")     # rejected, not ranked
        t = 0.0
        prev = None
        for node in self.backbone:
            c = assignment.get(node, LayoutChoice())
            if prev is not None:
                t += self.transition_cost(prev, c, node)
            t += self.node_cost(node, c)
            prev = c
        # non-backbone ops run data-parallel at the dominant dp degree
        dp = max((c.dp for c in assignment.values()), default=1)
        if dp not in self._rest_time:
            self._rest_time[dp] = sum(
                self.sim.op_time(n, self.shapes, shard_factor=dp)
                for n in self._rest)
        return t + self._rest_time[dp]


class SearchedStrategy(Strategy):
    """Annotates the graph from a searched assignment: placeholders get the
    dp batch split; each backbone node's weight gets its tp split."""

    def __init__(self, assignment, mesh):
        self.assignment = assignment
        self.mesh = mesh

    def annotate(self, eval_nodes):
        dp = self.mesh.shape.get("dp", 1)
        tp = self.mesh.shape.get("tp", 1)
        for n in find_topo_sort(eval_nodes):
            if isinstance(n, PlaceholderOp) and dp > 1:
                n.dist_state = DistState({0: "dp"})
        for node, choice in self.assignment.items():
            if choice.tp > 1 and tp > 1:
                w = _weight_of(node)
                if w is not None and w.shape[-1] % tp == 0:
                    w.dist_state = DistState({len(w.shape) - 1: "tp"})
        return self.mesh


def _assignment_mesh(assignment, ndev):
    dp = max((c.dp for c in assignment.values()), default=1)
    tp = max((c.tp for c in assignment.values()), default=1)
    axes = {}
    if dp > 1 or tp == 1:
        axes["dp"] = dp
    if tp > 1:
        axes["tp"] = tp
    if not axes:
        axes = {"dp": 1}
    return make_mesh(axes)


class HeterogeneousStrategy(Strategy):
    """Per-node layouts on ONE binary-factored mesh (m0..m{k-1}, 2^k
    devices).  A node with (dp, tp) shards its batch dim over the first
    log2(dp) axes and its weight/output feature dim over the next
    log2(tp) axes; differently-laid-out neighbors meet at
    with_sharding_constraint reshard points (graph/trace.py lowers
    interior dist_state annotations), where GSPMD inserts the
    collectives the reference emitted as cross_send/cross_receive
    (context.py:1658).  This keeps FlexFlow's per-node heterogeneity —
    the point of the MCMC — instead of projecting onto one grid.
    """

    def __init__(self, assignment, ndev, shapes=None, ndims=None):
        self.assignment = dict(assignment)
        k = int(math.log2(ndev)) if ndev > 1 else 0
        assert 2 ** k == ndev, f"heterogeneous mesh needs 2^k devices, " \
                               f"got {ndev}"
        self.k = k
        self.axes = tuple(f"m{i}" for i in range(k))
        self.mesh = make_mesh({a: 2 for a in self.axes}) if k else \
            make_mesh({"m0": 1})
        self._shapes = shapes or {}
        self._ndims = ndims or {}   # node name -> output ndim (persisted)

    def _split(self, choice):
        a = int(math.log2(choice.dp)) if choice.dp > 1 else 0
        b = int(math.log2(choice.tp)) if choice.tp > 1 else 0
        assert a + b <= self.k
        dp_axes = self.axes[:a]
        tp_axes = self.axes[a:a + b]
        return dp_axes, tp_axes

    @staticmethod
    def _axis_entry(axes):
        if not axes:
            return None
        return axes[0] if len(axes) == 1 else tuple(axes)

    def annotate(self, eval_nodes):
        first_dp = None
        for node, choice in self.assignment.items():
            dp_axes, tp_axes = self._split(choice)
            if not first_dp:
                # first NON-empty dp axes: a leading tp-only entry (dp=1,
                # empty axes) must not lock batch placeholders to replicated
                first_dp = dp_axes
            splits = {}
            if dp_axes:
                splits[0] = self._axis_entry(dp_axes)
            out = self._shapes.get(node)
            if out is not None:
                ndim = len(out.shape)
            else:
                ndim = self._ndims.get(node.name, 2)
            if tp_axes and ndim >= 2:
                splits[ndim - 1] = self._axis_entry(tp_axes)
            node.dist_state = DistState(splits) if splits else None
            w = _weight_of(node)
            if w is not None and tp_axes:
                w.dist_state = DistState(
                    {len(w.shape) - 1: self._axis_entry(tp_axes)})
        # batch-bearing placeholders follow the first backbone node's dp
        if first_dp:
            for n in find_topo_sort(eval_nodes):
                if isinstance(n, PlaceholderOp) and n.dist_state is None:
                    if n.shape and len(n.shape) >= 1:
                        n.dist_state = DistState(
                            {0: self._axis_entry(first_dp)})
        return self.mesh

    # -- persistence (reference Strategy.save_json base.py:183) -----------
    def config(self):
        def ndim_of(n):
            out = self._shapes.get(n)
            if out is not None:
                return len(out.shape)
            return self._ndims.get(n.name, 2)

        return {"strategy": "HeterogeneousStrategy", "ndev": 2 ** self.k,
                "assignment": {n.name: [c.dp, c.tp, ndim_of(n)]
                               for n, c in self.assignment.items()}}

    @classmethod
    def from_config(cls, cfg, eval_nodes):
        """Rebuild against a (re-constructed) graph: nodes resolved by
        name, so the same model-building code must have produced them.
        Output ranks travel in the config, so restored strategies place
        tp splits on the same (last) axis the search scored."""
        by_name = {n.name: n for n in find_topo_sort(eval_nodes)}
        assignment, ndims = {}, {}
        for name, entry in cfg["assignment"].items():
            dp, tp = entry[0], entry[1]
            node = by_name.get(name)
            if node is None:
                raise KeyError(
                    f"searched node {name!r} absent from the graph — "
                    "was the model rebuilt with different names?")
            assignment[node] = LayoutChoice(dp=dp, tp=tp,
                                            tp_dim=1 if tp > 1 else None)
            if len(entry) > 2:
                ndims[name] = int(entry[2])
        return cls(assignment, cfg["ndev"], ndims=ndims)


class OptCNNSearch:
    """DP over the backbone chain (reference optcnn.py:9): state = layout of
    the current backbone node; edge = reshard cost between layouts."""

    def __init__(self, ndev=None, simulator=None, measure=True,
                 mem_budget_bytes=None, opt_slots_mult=3.0):
        self.ndev = ndev
        self.sim = simulator
        self.measure = measure
        self.mem_budget_bytes = mem_budget_bytes
        self.opt_slots_mult = opt_slots_mult

    def search(self, eval_nodes, feed_shapes=None):
        import jax
        ndev = self.ndev or len(jax.devices())
        cost = GraphCost(eval_nodes, ndev, self.sim, feed_shapes,
                         mem_budget_bytes=self.mem_budget_bytes,
                         opt_slots_mult=self.opt_slots_mult)
        cost.maybe_record(self.measure, feed_shapes)
        chain = cost.backbone
        if not chain:
            return SearchedStrategy({}, make_mesh({"dp": 1}))
        cands = [candidate_choices(n, cost.shapes, ndev) for n in chain]
        # uniform mesh constraint: every node must use the same (dp, tp)
        # grid shape to live on one mesh; enumerate grids, DP inside
        best = (float("inf"), None)
        grids = sorted({(c.dp, c.tp) for cc in cands for c in cc})
        for dp, tp in grids:
            assign = {}
            feasible = True
            for n, cc in zip(chain, cands):
                match = [c for c in cc if (c.dp, c.tp) == (dp, tp)]
                if not match:  # this node can't take the grid; replicate tp
                    match = [c for c in cc if (c.dp, c.tp) == (dp, 1)]
                if not match:
                    feasible = False
                    break
                assign[n] = match[0]
            if not feasible:
                continue
            t = cost.total(assign)
            if t < best[0]:
                best = (t, assign)
        t, assign = best
        if assign is None:
            raise ValueError(
                "no grid satisfies the search constraints"
                + (f" (mem_budget_bytes={self.mem_budget_bytes}: every "
                   "candidate layout exceeds the per-device budget)"
                   if self.mem_budget_bytes is not None else ""))
        return SearchedStrategy(assign, _assignment_mesh(assign, ndev))


class FlexFlowSearch:
    """MCMC over per-node layouts (reference flexflow.py:12 — random
    proposals accepted by simulated delta with temperature).

    ``measure=True`` (default) profiles each distinct op once and feeds
    the simulator MEASURED times (disk-cached), the reference's
    profiling-backed simulate (base.py:663); roofline estimates only
    back-fill ops that fail to profile.  ``project=True`` collapses the
    result onto one (dp, tp) grid (the round-1 behavior); the default
    keeps per-node heterogeneity via HeterogeneousStrategy.
    """

    def __init__(self, ndev=None, simulator=None, iters=200, temp=1e-4,
                 seed=0, measure=True, project=False,
                 mem_budget_bytes=None, opt_slots_mult=3.0):
        self.ndev = ndev
        self.sim = simulator
        self.iters = iters
        self.temp = temp
        self.measure = measure
        self.project = project
        self.mem_budget_bytes = mem_budget_bytes
        self.opt_slots_mult = opt_slots_mult
        self.rng = np.random.default_rng(seed)

    def search(self, eval_nodes, feed_shapes=None):
        import jax
        ndev = self.ndev or len(jax.devices())
        cost = GraphCost(eval_nodes, ndev, self.sim, feed_shapes,
                         mem_budget_bytes=self.mem_budget_bytes,
                         opt_slots_mult=self.opt_slots_mult)
        cost.maybe_record(self.measure, feed_shapes)
        chain = cost.backbone
        if not chain:
            return SearchedStrategy({}, make_mesh({"dp": 1}))
        cands = {n: candidate_choices(n, cost.shapes, ndev) for n in chain}
        # start from pure DP at the largest feasible degree; if a memory
        # budget makes that start INFEASIBLE (replicated weights too
        # big), re-seed from the most memory-frugal assignment (max tp
        # everywhere) — single-node MCMC moves cannot cross a wide
        # infeasible region (inf -> inf moves carry no gradient), so the
        # walk must START inside the feasible set
        assign = {}
        for n in chain:
            dps = [c for c in cands[n] if c.tp == 1]
            assign[n] = max(dps, key=lambda c: c.dp)
        if not cost.feasible(assign):
            for n in chain:
                assign[n] = max(cands[n],
                                key=lambda c: (c.tp, -c.dp))
            if not cost.feasible(assign):
                # neither corner fits: sweep the uniform (dp, tp) grids
                # (mixed layouts can fit when pure-dp blows the weight
                # budget AND pure-tp blows the dp-unsharded activations)
                grids = sorted({(c.dp, c.tp)
                                for cc in cands.values() for c in cc})
                for dp, tp in grids:
                    trial = {}
                    for n in chain:
                        match = [c for c in cands[n]
                                 if (c.dp, c.tp) == (dp, tp)] or \
                                [c for c in cands[n]
                                 if (c.dp, c.tp) == (dp, 1)]
                        if not match:
                            break
                        trial[n] = match[0]
                    if len(trial) == len(chain) and cost.feasible(trial):
                        assign = trial
                        break
                else:
                    raise ValueError(
                        "FlexFlow found no feasible assignment under "
                        f"mem_budget_bytes={self.mem_budget_bytes} (no "
                        "corner or uniform-grid layout fits the "
                        "per-device budget)")
        cur = cost.total(assign)
        best, best_assign = cur, dict(assign)
        for _ in range(self.iters):
            n = chain[self.rng.integers(len(chain))]
            prop = cands[n][self.rng.integers(len(cands[n]))]
            old = assign[n]
            if prop == old:
                continue
            assign[n] = prop
            t = cost.total(assign)
            if t < cur:
                accept = True
            elif math.isinf(t) or math.isinf(cur):
                accept = False      # inf-inf would NaN the Metropolis test
            else:
                accept = self.rng.random() < math.exp(
                    -(t - cur) / max(self.temp, 1e-12))
            if accept:
                cur = t
                if t < best:
                    best, best_assign = t, dict(assign)
            else:
                assign[n] = old
        if math.isinf(best):
            raise ValueError(
                "FlexFlow found no feasible assignment"
                + (f" under mem_budget_bytes={self.mem_budget_bytes}"
                   if self.mem_budget_bytes is not None else ""))
        if not self.project:
            # keep the heterogeneous per-node result — restrict choices to
            # power-of-two shard counts the binary mesh can express
            k = int(math.log2(ndev)) if ndev > 1 else 0
            hetero = {n: c for n, c in best_assign.items()
                      if (c.dp & (c.dp - 1)) == 0
                      and (c.tp & (c.tp - 1)) == 0
                      and c.dp * c.tp <= 2 ** k}
            return HeterogeneousStrategy(hetero, 2 ** k,
                                         shapes=cost.shapes)
        # legacy projection: try every grid the chain visited, re-score
        # each projected assignment, keep the cheapest
        grids = {(c.dp, c.tp) for c in best_assign.values()}
        grids.add((max(c.dp for c in assign.values()), 1))  # pure-DP anchor
        proj_best = (float("inf"), None)
        for dp, tp in grids:
            proj = {}
            for n in chain:
                match = [c for c in cands[n] if (c.dp, c.tp) == (dp, tp)] or \
                    [c for c in cands[n] if (c.dp, c.tp) == (dp, 1)] or \
                    [LayoutChoice()]
                proj[n] = match[0]
            t = cost.total(proj)
            if t < proj_best[0]:
                proj_best = (t, proj)
        if proj_best[1] is None:
            raise ValueError(
                "no single-grid projection of the FlexFlow result is "
                "feasible under the memory budget; use project=False")
        best_assign = proj_best[1]
        return SearchedStrategy(best_assign,
                                _assignment_mesh(best_assign, ndev))


# ---------------------------------------------------------------------------
# pipeline stage partitioning


def partition_stages(layer_times, n_stages, boundary_bytes=None,
                     simulator=None):
    """Split L layers into n_stages contiguous stages minimizing the max
    stage time (+ boundary p2p) — the GPipe partition DP (reference
    gpipe.py:6).  Returns list of (start, end) half-open layer ranges."""
    sim = simulator or HetuSimulator()
    L = len(layer_times)
    n_stages = min(n_stages, L)
    prefix = np.concatenate([[0.0], np.cumsum(layer_times)])

    def seg(i, j):  # layers [i, j)
        t = prefix[j] - prefix[i]
        if boundary_bytes is not None and j < L:
            t += sim.collective_time(boundary_bytes, 2, "p2p")
        return t

    INF = float("inf")
    dp = np.full((L + 1, n_stages + 1), INF)
    cut = np.zeros((L + 1, n_stages + 1), np.int64)
    dp[0][0] = 0.0
    for j in range(1, L + 1):
        for s in range(1, n_stages + 1):
            for i in range(s - 1, j):
                v = max(dp[i][s - 1], seg(i, j))
                if v < dp[j][s]:
                    dp[j][s] = v
                    cut[j][s] = i
    bounds = []
    j = L
    for s in range(n_stages, 0, -1):
        i = cut[j][s]
        bounds.append((int(i), int(j)))
        j = i
    return list(reversed(bounds))


class GPipeSearch:
    """Choose the stage partition for a GPipe schedule; the bubble term
    (S-1)/(M+S-1) only shifts the optimum when M is small, so the cost is
    (M + S - 1) * max_stage / M."""

    def __init__(self, n_stages, n_micro, simulator=None):
        self.n_stages, self.n_micro = n_stages, n_micro
        self.sim = simulator or HetuSimulator()

    def search(self, layer_times, boundary_bytes=None):
        bounds = partition_stages(layer_times, self.n_stages,
                                  boundary_bytes, self.sim)
        # partition_stages clamps to len(layer_times); the bubble term must
        # use the stage count actually realized
        s = len(bounds)
        prefix = np.concatenate([[0.0], np.cumsum(layer_times)])
        max_stage = max(prefix[j] - prefix[i] for i, j in bounds)
        t = (self.n_micro + s - 1) * max_stage / self.n_micro
        return bounds, float(t)


class PipeDreamSearch(GPipeSearch):
    """1F1B-flush variant (reference pipedream.py:7): same steady-state
    bubble as GPipe-flush, but stage memory is bounded by in-flight
    micro-batches (S - stage_index), which the partition respects via a
    per-stage activation cap."""

    def search(self, layer_times, boundary_bytes=None, act_bytes_per_layer=0,
               mem_cap=None):
        bounds, t = super().search(layer_times, boundary_bytes)
        if mem_cap and act_bytes_per_layer:
            for idx, (i, j) in enumerate(bounds):
                in_flight = len(bounds) - idx
                need = (j - i) * act_bytes_per_layer * in_flight
                if need > mem_cap:
                    t = float("inf")  # infeasible under the cap
        return bounds, t


class PipeOptSearch:
    """Joint (pp degree, micro-batch count) search (reference pipeopt.py:9):
    try every pp that divides ndev, partition stages, pick the best
    estimated step time; remaining devices become dp replicas."""

    def __init__(self, ndev, simulator=None, micro_candidates=(1, 2, 4, 8,
                                                               16, 32)):
        self.ndev = ndev
        self.sim = simulator or HetuSimulator()
        self.micro_candidates = micro_candidates

    def search(self, layer_times, boundary_bytes=None):
        best = None
        pp = 1
        while pp <= self.ndev:
            for m in self.micro_candidates:
                bounds, t = GPipeSearch(pp, m, self.sim).search(
                    layer_times, boundary_bytes)
                real_pp = len(bounds)  # partition may clamp pp to #layers
                # dp replicas scale throughput linearly
                dp = self.ndev // real_pp
                eff = t / max(dp, 1)
                if best is None or eff < best["time"]:
                    best = {"pp": real_pp, "dp": dp, "n_micro": m,
                            "bounds": bounds, "time": eff}
            pp *= 2
        return best
