"""Variable initializers (reference: /root/reference/python/hetu/initializers.py).

Each initializer is a callable ``(key, shape, dtype) -> jax.Array``; Variables
hold one and the executor materializes values at construction time.  The
reference's curand kernels (src/ops/Initializers.cu) become jax.random calls;
``init_on_ps`` (PS-side init) has its TPU equivalent in ps/ (host store init).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


class Initializer:
    def __call__(self, key, shape, dtype=jnp.float32):
        raise NotImplementedError


class ConstantInit(Initializer):
    def __init__(self, constant=0.0):
        self.constant = constant

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.constant, dtype=dtype)


class ZerosInit(ConstantInit):
    def __init__(self):
        super().__init__(0.0)


class OnesInit(ConstantInit):
    def __init__(self):
        super().__init__(1.0)


class UniformInit(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype=dtype,
                                  minval=self.low, maxval=self.high)


class NormalInit(Initializer):
    def __init__(self, mean=0.0, stddev=1.0):
        self.mean, self.stddev = mean, stddev

    def __call__(self, key, shape, dtype=jnp.float32):
        return self.mean + self.stddev * jax.random.normal(key, shape, dtype=dtype)


class TruncatedNormalInit(Initializer):
    def __init__(self, mean=0.0, stddev=1.0):
        self.mean, self.stddev = mean, stddev

    def __call__(self, key, shape, dtype=jnp.float32):
        return self.mean + self.stddev * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype=dtype)


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels (O, I, H, W) layout
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormalInit(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype=dtype)


class XavierUniformInit(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype=dtype,
                                  minval=-limit, maxval=limit)


class HeNormalInit(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        return math.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype=dtype)


class HeUniformInit(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        limit = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype=dtype,
                                  minval=-limit, maxval=limit)


class LecunNormalInit(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        return math.sqrt(1.0 / fan_in) * jax.random.normal(key, shape, dtype=dtype)


class NumpyInit(Initializer):
    """Wraps a concrete numpy array (reference: provided-value Variables)."""

    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, key, shape, dtype=jnp.float32):
        assert tuple(shape) == tuple(self.value.shape), \
            f"shape mismatch {shape} vs {self.value.shape}"
        return jnp.asarray(self.value, dtype=dtype)


# functional aliases matching the reference's API names
def zeros(): return ZerosInit()
def ones(): return OnesInit()
def constant(c=0.0): return ConstantInit(c)
def uniform(low=-1.0, high=1.0): return UniformInit(low, high)
def normal(mean=0.0, stddev=1.0): return NormalInit(mean, stddev)
def truncated_normal(mean=0.0, stddev=1.0): return TruncatedNormalInit(mean, stddev)
def xavier_normal(gain=1.0): return XavierNormalInit(gain)
def xavier_uniform(gain=1.0): return XavierUniformInit(gain)
def he_normal(): return HeNormalInit()
def he_uniform(): return HeUniformInit()
def lecun_normal(): return LecunNormalInit()
