"""GNN layers + 1.5-D distributed GCN.

Reference: python/hetu/gpu_ops/DistGCN_15d.py:19-155 — GCN propagation
Z = A @ (H W) with the adjacency row-partitioned across P/c process rows,
features replicated c ways; per-stage NCCL broadcasts stream the feature
blocks through col groups and a row-group allreduce combines the partial
products (CuSparse_Csrmm per stage).

TPU redesign: the broadcast-round pipeline IS a sharding. On a
(block=P/c, rep=c) mesh, the same computation is a single matmul with
  A sharded (rows -> 'block', cols -> 'rep'),
  HW row-sharded over 'rep' (replicated over 'block'),
  partial products psum'd over 'rep',
and XLA lowers the data movement to the minimal ICI collectives — no
hand-scheduled stages.  The adjacency is kept as dense normalized blocks
(MXU-friendly; GCN adjacencies at TPU-worthwhile sizes are usually
blocked/sampled anyway); the single-device path offers a segment-sum SpMM
for COO graphs (gcn_conv).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..platform import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..graph.node import Op
from ..ops.base import simple_op


# -- single-device sparse GCN conv (COO segment-sum) ----------------------

def _gcn_conv(h, w, src=None, dst=None, edge_weight=None, num_nodes=None):
    """Z[dst] += a(src,dst) * (H W)[src] — SpMM as gather + segment-sum
    (reference CuSparseCsrmm.cu path through DistGCN's need_W branch)."""
    hw = jnp.matmul(h, w, preferred_element_type=jnp.float32)
    n = num_nodes or h.shape[0]
    gathered = hw[jnp.asarray(src, jnp.int32)]
    if edge_weight is not None:
        gathered = gathered * edge_weight[:, None]
    return jax.ops.segment_sum(gathered, jnp.asarray(dst, jnp.int32),
                               num_segments=n).astype(h.dtype)


gcn_conv_op = simple_op(_gcn_conv, "gcn_conv")


def normalized_adjacency(src, dst, num_nodes, add_self_loops=True):
    """Dense sym-normalized adjacency D^-1/2 (A+I) D^-1/2 (GCN propagation
    matrix), numpy-side model prep."""
    a = np.zeros((num_nodes, num_nodes), np.float32)
    a[dst, src] = 1.0
    a = np.maximum(a, a.T)   # GCN treats the graph as undirected
    if add_self_loops:
        a[np.arange(num_nodes), np.arange(num_nodes)] = 1.0
    deg = a.sum(1)
    dinv = 1.0 / np.sqrt(np.maximum(deg, 1.0))
    return a * dinv[:, None] * dinv[None, :]


# -- 1.5-D distributed propagation ----------------------------------------

class DistGCN15D:
    """Z = A @ (H @ W) on a (block, rep) mesh.

    * adjacency `a` enters sharded (P('block', 'rep')): each device holds an
      (N/block, N/rep) tile — the reference's row-partition with the stage
      loop's column range materialized as the 'rep' shard.
    * features `h` enter row-sharded over 'rep' (the c-fold replication of
      the reference becomes: each rep rank holds the feature rows its
      column-stages need, replicated across 'block').
    * the local tile matmul runs on the MXU; `psum` over 'rep' plays the
      row-group allreduce (DistGCN_15d.py:66-68).
    """

    def __init__(self, mesh, block_axis="block", rep_axis="rep"):
        self.mesh = mesh
        self.block_axis = block_axis
        self.rep_axis = rep_axis
        self._fn = jax.jit(self.propagate_fn())   # compile once

    def propagate_fn(self):
        ba, ra = self.block_axis, self.rep_axis

        def body(a_tile, h_rows, w):
            hw = jnp.matmul(h_rows, w, preferred_element_type=jnp.float32)
            partial = jnp.matmul(a_tile, hw,
                                 preferred_element_type=jnp.float32)
            return lax.psum(partial, ra)

        return shard_map(
            body, mesh=self.mesh,
            in_specs=(P(ba, ra), P(ra, None), P()),
            out_specs=P(ba, None))

    def __call__(self, a, h, w, activation=None):
        out = self._fn(a, h, w)
        if activation is not None:
            out = activation(out)
        return out


class GCNLayerOp(Op):
    """Graph-node wrapper of gcn_conv for the define-then-run API."""

    def __init__(self, h, w, src, dst, edge_weight=None, num_nodes=None,
                 name=None):
        inputs = [h, w, src, dst]
        if edge_weight is not None:
            inputs.append(edge_weight)
        super().__init__(*inputs, name=name)
        self.num_nodes = num_nodes
        self.has_ew = edge_weight is not None

    def _compute(self, input_vals, ctx):
        h, w, src, dst = input_vals[:4]
        ew = input_vals[4] if self.has_ew else None
        return _gcn_conv(h, w, src=src, dst=dst, edge_weight=ew,
                         num_nodes=self.num_nodes)


def distgcn_15d_op(h, w, src, dst, edge_weight=None, num_nodes=None,
                   name=None):
    return GCNLayerOp(h, w, src, dst, edge_weight=edge_weight,
                      num_nodes=num_nodes, name=name)
