"""KV-cache autoregressive decoding for the Llama tier.

The reference is a training system; its deployment story is export
(ONNX / switchinference forms).  A modern-LLM tier needs generation, so
this module adds a TPU-native decode path: one jitted program containing
prompt prefill + a ``lax.scan`` over decode steps with a scan-carried
K/V cache — static shapes throughout (cache preallocated at
prompt_len + max_new, future positions masked), so XLA compiles exactly
two matmul-shaped programs regardless of how many tokens are generated.

It consumes an Executor's params by the canonical variable names
(models/llama.py naming), so a trained or HF-imported model decodes
without graph changes:

    fn = build_greedy_decode(config, max_new=32, name="llama")
    tokens = fn(ex.params, prompt_ids)     # [B, P+32] int32

Greedy decoding matches transformers' ``generate(do_sample=False)``
token-for-token (tests/test_torch_parity.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.rotary import _rope_tables
from ._decode_common import (make_picker, make_attend, assemble,
                             param_prefix, executor_generate)


def _rms(x, g, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), -1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g


def _rotate(x, cos, sin):
    """x [..., S, D] with per-position cos/sin [S, D] (rotate_half)."""
    d = x.shape[-1]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return (xf * cos + rot * sin).astype(x.dtype)


def make_layer_params(config, name, moe_names=None):
    """Per-layer param lookup by the canonical models/llama.py naming;
    returns ``layer_params(params, i) -> dict`` (shared with serving)."""
    c = config

    def layer_params(params, i):
        our = f"{name}_layer{i}"
        out = {
            "in_norm": params[f"{our}_input_norm_scale"],
            "post_norm": params[f"{our}_post_norm_scale"],
            "wq": params[f"{our}_attn_q_weight"],
            "wk": params[f"{our}_attn_k_weight"],
            "wv": params[f"{our}_attn_v_weight"],
            "wo": params[f"{our}_attn_out_weight"],
        }
        if c.num_experts:
            # sparse-MoE blocks (Mixtral-style): variable names resolved
            # by the caller from the layer objects (moe_names), since
            # fresh_name may suffix the router gate
            nm = moe_names[i]
            out.update(wg=params[nm["wg"]], ew1=params[nm["w1"]],
                       ew2=params[nm["w2"]], ew3=params[nm["w3"]])
        else:
            out.update(gate=params[f"{our}_mlp_gate_weight"],
                       up=params[f"{our}_mlp_up_weight"],
                       down=params[f"{our}_mlp_out_weight"])
        return out

    return layer_params


def make_block(config, gather=None):
    """One Llama decoder layer over an explicit K/V cache; returns
    ``block(lp, x [B, Sq, H], cache_k, cache_v [B, KV, T, D], cos, sin,
    pos_mask, write_at) -> (x', cache_k', cache_v')``.  Used by both the
    one-shot greedy decoder and the slot-batched serving engine.

    ``gather`` (tensor-parallel serving, serving/sharding.py): a hook
    constraining an activation back to replicated, applied before each
    op that reduces over a sharded axis — the attention output before
    ``wo``, the MLP activation before ``down``, and both residual sums
    (the next norm reduces over hidden).  All-gathers move bytes
    exactly, so the sharded block stays a bitwise twin of the
    unsharded one.  Identity (free) when not tensor-parallel."""
    c = config
    hd = c.hidden_size // c.num_heads
    attend = make_attend(hd, c.num_heads // c.num_kv_heads)
    g = gather if gather is not None else (lambda x: x)

    def moe_ffn(lp, f):
        """Dense-combine top-k MoE for decode: every expert computes, the
        router's top-k renormalized weights combine.  Correct for any
        batch; the bandwidth-optimal per-token expert gather is a decode
        optimization, not a semantics change."""
        probs = jax.nn.softmax((f @ lp["wg"]).astype(jnp.float32), -1)
        topv, topi = jax.lax.top_k(probs, c.moe_k)        # [B, S, k]
        w = topv / jnp.sum(topv, -1, keepdims=True)
        e_w = jnp.sum(jax.nn.one_hot(topi, c.num_experts,
                                     dtype=w.dtype) * w[..., None],
                      axis=-2)                            # [B, S, E]
        a = (jax.nn.silu(jnp.einsum("bsh,ehf->bsef", f, lp["ew1"]))
             * jnp.einsum("bsh,ehf->bsef", f, lp["ew3"]))
        y = jnp.einsum("bsef,efh->bseh", a, lp["ew2"])
        return jnp.einsum("bse,bseh->bsh", e_w.astype(y.dtype), y)

    def block(lp, x, cache_k, cache_v, cos, sin, pos_mask, write_at):
        """x [B, Sq, H]; returns (x', cache_k', cache_v')."""
        b, sq, _ = x.shape
        h = _rms(x, lp["in_norm"], c.rms_eps)
        q = (h @ lp["wq"]).reshape(b, sq, c.num_heads, hd)
        k = (h @ lp["wk"]).reshape(b, sq, c.num_kv_heads, hd)
        v = (h @ lp["wv"]).reshape(b, sq, c.num_kv_heads, hd)
        q = _rotate(q.transpose(0, 2, 1, 3), cos, sin)
        k = _rotate(k.transpose(0, 2, 1, 3), cos, sin)
        v = v.transpose(0, 2, 1, 3)
        cache_k = jax.lax.dynamic_update_slice_in_dim(cache_k, k, write_at,
                                                      axis=2)
        cache_v = jax.lax.dynamic_update_slice_in_dim(cache_v, v, write_at,
                                                      axis=2)
        o = attend(q, cache_k, cache_v, pos_mask)
        o = g(o.transpose(0, 2, 1, 3).reshape(b, sq, c.hidden_size))
        x = g(x + o @ lp["wo"])
        f = _rms(x, lp["post_norm"], c.rms_eps)
        if c.num_experts:
            return g(x + moe_ffn(lp, f)), cache_k, cache_v
        return (g(x + g(jax.nn.silu(f @ lp["gate"]) * (f @ lp["up"]))
                @ lp["down"]), cache_k, cache_v)

    return block


def make_chunk_embed(config, name):
    """Embedding + rotary rows + mask for one prefill CHUNK per lane.

    Returns ``chunk_inputs(params, tokens [B, C], starts [B], t) ->
    (x [B, C, H], cos [B, C, hd], sin [B, C, hd], mask [B, C, t])``
    where lane ``i``'s chunk occupies global rows ``[starts[i],
    starts[i] + C)`` of a ``t``-row cache.  Rows are gathered (never
    ``dynamic_slice``d — an out-of-range start would silently CLAMP
    and shift valid rows) and clipped for the pad tail; the mask stays
    exact because it derives from the unclipped rows."""
    c = config
    hd = c.hidden_size // c.num_heads

    def chunk_inputs(params, tokens, starts, t):
        emb = params[f"{name}_embed_table"]
        cos_t, sin_t = _rope_tables(t, hd, c.rope_theta)
        cl = tokens.shape[1]
        rows = starts[:, None] + jnp.arange(cl)[None, :]     # [B, C]
        rc = jnp.clip(rows, 0, t - 1)
        mask = jnp.arange(t)[None, None, :] <= rows[:, :, None]
        return emb[tokens], cos_t[rc], sin_t[rc], mask

    return chunk_inputs


def make_logits(config, name):
    """Final-norm + LM-head projection shared by decode paths."""
    c = config

    def logits_of(params, h_last):
        h = _rms(h_last, params[f"{name}_norm_scale"], c.rms_eps)
        if c.tie_embeddings:
            return h @ params[f"{name}_embed_table"].T
        return h @ params[f"{name}_lm_head_weight"]

    return logits_of


def build_greedy_decode(config, max_new, name="llama", temperature=0.0,
                        top_k=0, moe_names=None):
    """Returns jitted ``fn(params, prompt_ids [B, P][, key]) ->
    [B, P+max_new]``.

    ``temperature`` 0 = greedy argmax; > 0 samples from
    softmax(logits/temperature), restricted to the ``top_k`` largest
    logits when top_k > 0 (pass a jax PRNG key as the third argument).
    The prompt length is baked at first call (a new P retraces, the
    executor's usual static-shape contract)."""
    c = config
    hd = c.hidden_size // c.num_heads

    layer_params = make_layer_params(c, name, moe_names)
    block = make_block(c)
    logits_of = make_logits(c, name)
    pick = make_picker(temperature, top_k)

    @jax.jit
    def decode(params, prompt_ids, key=None):
        if key is None:
            key = jax.random.key(0)
        b, p_len = prompt_ids.shape
        total = p_len + max_new
        cos_t, sin_t = _rope_tables(total, hd, c.rope_theta)
        emb = params[f"{name}_embed_table"]
        lps = [layer_params(params, i) for i in range(c.num_layers)]
        kshape = (b, c.num_kv_heads, total, hd)
        dtype = emb.dtype

        # ---- prefill: prompt through all layers, fill cache[0:P] -------
        x = emb[prompt_ids]
        caches = []
        pre_mask = (jnp.arange(total)[None, :]
                    <= jnp.arange(p_len)[:, None])   # [P, total] causal
        for lp in lps:
            ck = jnp.zeros(kshape, dtype)
            cv = jnp.zeros(kshape, dtype)
            x, ck, cv = block(lp, x, ck, cv, cos_t[:p_len], sin_t[:p_len],
                              pre_mask, 0)
            caches.append((ck, cv))
        key, k0 = jax.random.split(key)
        first = pick(logits_of(params, x[:, -1:, :]),
                     k0).astype(prompt_ids.dtype)              # [B, 1]

        # ---- decode: scan over single-token steps ----------------------
        def step(carry, t):
            tok, caches, key = carry
            key, kt = jax.random.split(key)
            pos = p_len + t                              # dynamic scalar
            x = emb[tok]                                  # [B, 1, H]
            cos = jax.lax.dynamic_slice_in_dim(cos_t, pos, 1, 0)
            sin = jax.lax.dynamic_slice_in_dim(sin_t, pos, 1, 0)
            mask = (jnp.arange(total) <= pos)[None, :]    # [1, total]
            new_caches = []
            for lp, (ck, cv) in zip(lps, caches):
                x, ck, cv = block(lp, x, ck, cv, cos, sin, mask, pos)
                new_caches.append((ck, cv))
            nxt = pick(logits_of(params, x), kt).astype(tok.dtype)
            return (nxt, new_caches, key), tok[:, 0]

        (last, _, _), toks = jax.lax.scan(
            step, (first, caches, key), jnp.arange(max_new - 1))
        return assemble(prompt_ids, first, last, toks, max_new)

    return decode


def moe_param_names(model):
    """Router/expert variable names per layer, resolved from the live
    layer objects (fresh_name may suffix the router gate)."""
    if not model.config.num_experts:
        return None
    return [{"wg": l.mlp.gate.wg.name, "w1": l.mlp.w1.name,
             "w2": l.mlp.w2.name, "w3": l.mlp.w3.name}
            for l in model.model.layers]


def greedy_generate(executor, model, prompt_ids, max_new, name=None,
                    temperature=0.0, top_k=0, seed=0):
    """Convenience wrapper: decode from an Executor's params.

    ``model``: the LlamaForCausalLM whose config/naming to use."""
    name = name or param_prefix(executor, "_embed_table")
    fn = build_greedy_decode(model.config, max_new, name=name,
                             temperature=temperature, top_k=top_k,
                             moe_names=moe_param_names(model))
    return executor_generate(fn, executor,
                             [jnp.asarray(prompt_ids, jnp.int32)], seed)
