"""Seq2seq Transformer (encoder-decoder with cross-attention).

Reference: examples/nlp/hetu_transformer.py — `Transformer` with
`encode` (self-attn blocks over the source), `decode` (causal self-attn
+ vanilla cross-attention over the encoder memory, embeddings shared
and tied to the output projection, both scaled by sqrt(d_model),
sinusoidal positions) and `train` (label-smoothed softmax CE);
hparams.py for the defaults (d_model 512, 6 blocks, 8 heads, eps 0.1).

TPU notes: positions are a precomputed constant table (host numpy →
device once); the loss masks pad positions like the reference's TF
companion (`tf_transformer.py` nonpadding) — the reference's hetu
variant averages pads in, which just rescales the loss by a constant
factor at fixed pad ratio.
"""

from __future__ import annotations

import numpy as np

from .. import initializers as init
from ..graph.node import VariableOp, name_scope
from ..layers import (LayerNorm, MultiHeadAttention, TransformerFFN,
                      TransformerLayer)
from ..ops import (array_reshape_op, dropout_op, embedding_lookup_op,
                   matmul_op, mul_op, one_hot_op, reduce_sum_op,
                   softmax_cross_entropy_op)


def sinusoidal_positions(max_len, d_model):
    """The standard sin/cos table (reference positional_encoding,
    hetu_transformer.py:161)."""
    pos = np.arange(max_len)[:, None].astype(np.float64)
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    table = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
    return table.astype(np.float32)


class TransformerConfig:
    def __init__(self, vocab_size=8000, d_model=128, num_blocks=2,
                 num_heads=8, d_ff=512, src_len=32, tgt_len=32,
                 dropout_rate=0.1, label_smoothing=0.1, pad_id=0):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.num_blocks = num_blocks
        self.num_heads = num_heads
        self.d_ff = d_ff
        self.src_len = src_len
        self.tgt_len = tgt_len
        self.dropout_rate = dropout_rate
        self.label_smoothing = label_smoothing
        self.pad_id = pad_id


class _DecoderBlock:
    def __init__(self, c, name):
        self.self_attn = MultiHeadAttention(c.d_model, c.num_heads,
                                            dropout_rate=c.dropout_rate,
                                            causal_mask=True,
                                            name=f"{name}_self")
        self.cross_attn = MultiHeadAttention(c.d_model, c.num_heads,
                                             dropout_rate=c.dropout_rate,
                                             name=f"{name}_cross")
        self.ffn = TransformerFFN(c.d_model, c.d_ff,
                                  dropout_rate=c.dropout_rate,
                                  name=f"{name}_ffn")
        self.ln1 = LayerNorm(c.d_model, name=f"{name}_ln1")
        self.ln2 = LayerNorm(c.d_model, name=f"{name}_ln2")
        self.ln3 = LayerNorm(c.d_model, name=f"{name}_ln3")

    def __call__(self, x, memory, tgt_mask, src_mask, tgt_len, src_len):
        x = self.ln1(x + self.self_attn(x, x, x, attention_mask=tgt_mask,
                                        seq_len=tgt_len))
        x = self.ln2(x + self.cross_attn(x, memory, memory,
                                         attention_mask=src_mask,
                                         seq_len=tgt_len,
                                         kv_seq_len=src_len))
        return self.ln3(x + self.ffn(x))


def _pad_bias(keep_f32, seq_len):
    """[B, S] 0/1 keep-mask (float) -> additive [B, 1, 1, S] bias
    (0 where kept, -1e9 at pads; reference src_masks/attention_mask)."""
    keep = array_reshape_op(keep_f32, output_shape=(-1, 1, 1, seq_len))
    return (keep - 1.0) * 1e9


class Seq2SeqTransformer:
    """Reference Transformer (hetu_transformer.py:186): shared scaled
    embeddings, sinusoidal positions, post-LN blocks, tied LM head."""

    def __init__(self, config, name="transformer"):
        c = self.config = config
        with name_scope():
            self.embeddings = VariableOp(
                f"{name}_embeddings", (c.vocab_size, c.d_model),
                init.xavier_normal())
            max_len = max(c.src_len, c.tgt_len)
            self.pos_table = VariableOp(
                f"{name}_positions", (max_len, c.d_model),
                init.NumpyInit(sinusoidal_positions(max_len, c.d_model)),
                trainable=False)
            # post-LN encoder block ≡ the shared TransformerLayer
            self.enc = [TransformerLayer(
                c.d_model, c.num_heads, c.d_ff,
                dropout_rate=c.dropout_rate,
                attn_dropout_rate=c.dropout_rate,
                name=f"{name}_enc{i}") for i in range(c.num_blocks)]
            self.dec = [_DecoderBlock(c, f"{name}_dec{i}")
                        for i in range(c.num_blocks)]

    def _embed(self, ids, seq_len):
        c = self.config
        from .bert import PositionIdsOp
        e = embedding_lookup_op(self.embeddings, ids) * (c.d_model ** 0.5)
        e = e + PositionIdsOp(self.pos_table, e, seq_len)
        if c.dropout_rate:
            e = dropout_op(e, keep_prob=1.0 - c.dropout_rate)
        return e

    def encode(self, src_ids, src_keep):
        c = self.config
        x = self._embed(src_ids, c.src_len)
        mask = _pad_bias(src_keep, c.src_len)
        for blk in self.enc:
            x = blk(x, attention_mask=mask, seq_len=c.src_len)
        return x

    def decode(self, tgt_in_ids, memory, src_keep, tgt_keep):
        c = self.config
        x = self._embed(tgt_in_ids, c.tgt_len)
        tgt_mask = _pad_bias(tgt_keep, c.tgt_len)
        src_mask = _pad_bias(src_keep, c.src_len)
        for blk in self.dec:
            x = blk(x, memory, tgt_mask, src_mask, c.tgt_len, c.src_len)
        flat = array_reshape_op(x, output_shape=(-1, c.d_model))
        logits = matmul_op(flat, self.embeddings, trans_B=True)
        return array_reshape_op(
            logits, output_shape=(-1, c.tgt_len, c.vocab_size))

    def __call__(self, src_ids, tgt_in_ids, src_keep, tgt_keep):
        memory = self.encode(src_ids, src_keep)
        return self.decode(tgt_in_ids, memory, src_keep, tgt_keep)

    def loss(self, src_ids, tgt_in_ids, tgt_out_ids, src_keep, tgt_keep):
        """Label-smoothed CE over non-pad target positions (reference
        train() + label_smoothing, with the TF companion's nonpadding
        normalization)."""
        c = self.config
        logits = self(src_ids, tgt_in_ids, src_keep, tgt_keep)
        onehot = one_hot_op(tgt_out_ids, num_classes=c.vocab_size)
        eps = c.label_smoothing
        smoothed = onehot * (1.0 - eps) + eps / c.vocab_size
        ce = softmax_cross_entropy_op(logits, smoothed)  # [B, T]
        ce = mul_op(ce, tgt_keep)
        denom = reduce_sum_op(tgt_keep) + 1e-7
        return reduce_sum_op(ce) / denom
