"""KV-cache greedy/sampled decoding for the seq2seq Transformer.

One jitted program: full encoder pass + per-layer cross-attention K/V
precomputed from the memory, then a `lax.scan` over decode steps with a
scan-carried self-attention cache — the same deployment story the
GPT/Llama tiers have (models/gpt_decode.py), extended with the
encoder-memory plumbing.  The reference's transformer has no decoding
path (training example only, examples/nlp/train_hetu_transformer.py) —
this goes beyond it the way llama_decode does.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ._decode_common import layer_norm as _ln
from ._decode_common import make_attend, make_picker, executor_generate


def build_seq2seq_decode(config, max_new, name="transformer",
                         temperature=0.0, top_k=0, bos_id=1):
    """Returns jitted ``fn(params, src_ids [B, S], src_keep [B, S]
    [, key]) -> [B, max_new]`` generated target tokens."""
    c = config
    h = c.num_heads
    hd = c.d_model // h
    if max_new > c.tgt_len:
        # positions past tgt_len were never used by the training decoder
        # (and dynamic_slice would silently clamp past the table end)
        raise ValueError(
            f"max_new={max_new} exceeds tgt_len={c.tgt_len}, the "
            f"positional range the decoder trained on; build the model "
            f"with a longer tgt_len to decode further")

    def attn_params(params, prefix):
        return {k: params[f"{prefix}_{v}"] for k, v in {
            "wq": "q_weight", "bq": "q_bias", "wk": "k_weight",
            "bk": "k_bias", "wv": "v_weight", "bv": "v_bias",
            "wo": "out_weight", "bo": "out_bias"}.items()}

    def split(x, n_seq):
        return x.reshape(-1, n_seq, h, hd).transpose(0, 2, 1, 3)

    attend = make_attend(hd)          # self-attention (shared [Sq, T] mask)
    pick = make_picker(temperature, top_k)

    def cross_attend(q, keys, vals, src_keep):
        """q [B,h,1,d] vs memory K/V [B,h,S,d] with per-batch pad bias."""
        s = jnp.einsum("bhqd,bhkd->bhqk", q, keys,
                       preferred_element_type=jnp.float32) / np.sqrt(hd)
        s = s + ((src_keep - 1.0) * 1e9)[:, None, None, :]
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(vals.dtype), vals,
                          preferred_element_type=jnp.float32
                          ).astype(vals.dtype)

    @jax.jit
    def decode(params, src_ids, src_keep, key=None):
        if key is None:
            key = jax.random.key(0)
        emb = params[f"{name}_embeddings"]
        pos = params[f"{name}_positions"]
        b, s_len = src_ids.shape
        scale = c.d_model ** 0.5
        sbias = ((src_keep - 1.0) * 1e9)[:, None, None, :]

        # ---- encoder (post-LN TransformerLayer semantics) ----
        x = emb[src_ids] * scale + pos[None, :s_len]
        for i in range(c.num_blocks):
            p = f"{name}_enc{i}"
            ap = attn_params(params, f"{p}_attn")
            q = split(x @ ap["wq"] + ap["bq"], s_len)
            k = split(x @ ap["wk"] + ap["bk"], s_len)
            v = split(x @ ap["wv"] + ap["bv"], s_len)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) \
                / np.sqrt(hd) + sbias
            o = jnp.einsum("bhqk,bhkd->bhqd",
                           jax.nn.softmax(s, -1).astype(v.dtype), v,
                           preferred_element_type=jnp.float32
                           ).astype(v.dtype)
            o = o.transpose(0, 2, 1, 3).reshape(b, s_len, c.d_model)
            x = _ln(x + o @ ap["wo"] + ap["bo"],
                    params[f"{p}_ln1_scale"], params[f"{p}_ln1_bias"])
            f = jax.nn.gelu(x @ params[f"{p}_ffn_in_weight"]
                            + params[f"{p}_ffn_in_bias"])
            x = _ln(x + f @ params[f"{p}_ffn_out_weight"]
                    + params[f"{p}_ffn_out_bias"],
                    params[f"{p}_ln2_scale"], params[f"{p}_ln2_bias"])
        memory = x

        # ---- per-layer decoder params + cross K/V computed once ----
        dec_ps, cross_kv = [], []
        for i in range(c.num_blocks):
            p = f"{name}_dec{i}"
            sp = attn_params(params, f"{p}_self")
            cp = attn_params(params, f"{p}_cross")
            dec_ps.append((p, sp, cp))
            cross_kv.append((split(memory @ cp["wk"] + cp["bk"], s_len),
                             split(memory @ cp["wv"] + cp["bv"], s_len)))

        def dec_step(tok, caches, t):
            """One decoder position: tok [B, 1] at absolute position t."""
            x = emb[tok] * scale + jax.lax.dynamic_slice_in_dim(
                pos, t, 1, 0)[None]
            self_mask = (jnp.arange(max_new) <= t)[None, :]
            new_caches = []
            for (p, sp, cp), (ck_x, cv_x), (ck, cv) in zip(
                    dec_ps, cross_kv, caches):
                q = split(x @ sp["wq"] + sp["bq"], 1)
                k1 = split(x @ sp["wk"] + sp["bk"], 1)
                v1 = split(x @ sp["wv"] + sp["bv"], 1)
                ck = jax.lax.dynamic_update_slice_in_dim(ck, k1, t,
                                                         axis=2)
                cv = jax.lax.dynamic_update_slice_in_dim(cv, v1, t,
                                                         axis=2)
                o = attend(q, ck, cv, self_mask)
                o = o.transpose(0, 2, 1, 3).reshape(-1, 1, c.d_model)
                x = _ln(x + o @ sp["wo"] + sp["bo"],
                        params[f"{p}_ln1_scale"],
                        params[f"{p}_ln1_bias"])
                qc = split(x @ cp["wq"] + cp["bq"], 1)
                oc = cross_attend(qc, ck_x, cv_x, src_keep)
                oc = oc.transpose(0, 2, 1, 3).reshape(-1, 1, c.d_model)
                x = _ln(x + oc @ cp["wo"] + cp["bo"],
                        params[f"{p}_ln2_scale"],
                        params[f"{p}_ln2_bias"])
                f = jax.nn.gelu(x @ params[f"{p}_ffn_in_weight"]
                                + params[f"{p}_ffn_in_bias"])
                x = _ln(x + f @ params[f"{p}_ffn_out_weight"]
                        + params[f"{p}_ffn_out_bias"],
                        params[f"{p}_ln3_scale"],
                        params[f"{p}_ln3_bias"])
                new_caches.append((ck, cv))
            return x @ emb.T, new_caches

        kshape = (b, h, max_new, hd)
        caches0 = [(jnp.zeros(kshape, emb.dtype),
                    jnp.zeros(kshape, emb.dtype))
                   for _ in range(c.num_blocks)]
        bos = jnp.full((b, 1), bos_id, src_ids.dtype)
        key, k0 = jax.random.split(key)
        logits, caches = dec_step(bos, caches0, 0)
        first = pick(logits[:, -1, :], k0).astype(src_ids.dtype)[:, None]

        def step(carry, t):
            tok, caches, key = carry
            key, kt = jax.random.split(key)
            logits, caches = dec_step(tok, caches, t + 1)
            nxt = pick(logits[:, -1, :], kt).astype(tok.dtype)[:, None]
            return (nxt, caches, key), tok[:, 0]

        if max_new == 1:
            return first
        (last, _, _), toks = jax.lax.scan(
            step, (first, caches, key), jnp.arange(max_new - 1))
        return jnp.concatenate([toks.transpose(1, 0), last], axis=1)

    return decode


def seq2seq_generate(executor, model, src_ids, src_keep, max_new,
                     name=None, temperature=0.0, top_k=0,
                     bos_id=1, seed=0):
    if name is None:
        # infer the param prefix from the model (llama_decode convention)
        name = model.embeddings.name.rsplit("_embeddings", 1)[0]
    fn = build_seq2seq_decode(model.config, max_new, name=name,
                              temperature=temperature, top_k=top_k,
                              bos_id=bos_id)
    return executor_generate(
        fn, executor, [jnp.asarray(src_ids, jnp.int32),
                       jnp.asarray(src_keep, jnp.float32)], seed)
