"""Llama / Baichuan decoder LMs (reference: tools/Hetu-Galvatron/galvatron/
models/llama/LlamaModel_sequential.py, models/baichuan/ — the reference's
modern-LLM tier under hybrid parallelism).

TPU-native rebuild: RMSNorm pre-norm blocks, SwiGLU FFN, rotary position
embeddings (or ALiBi for the Baichuan-13B shape), optional grouped-query
attention.  No learned position table — positions live in the rotation, so
the model serves any sequence length the attention envelope admits.
Parallelism comes from strategy annotations (parallel/strategies.py
MegatronLM) or a searched Galvatron config; ``pipeline_stages=k`` stages
construction for the graph pipeline executor exactly like GPTModel.
"""

from __future__ import annotations

from contextlib import nullcontext

import numpy as np

from ..graph.node import stage, scoped_init
from .. import initializers as init
from ..layers import Embedding, Linear, RMSNorm
from ..layers.base import BaseLayer
from ..layers.attention import MultiHeadAttention
from ..ops import (array_reshape_op, matmul_op, silu_op,
                   softmax_cross_entropy_sparse_op)
from .bert import MaskedMeanOp


class LlamaConfig:
    def __init__(self, vocab_size=32000, hidden_size=4096, num_layers=32,
                 num_heads=32, num_kv_heads=None, intermediate_size=11008,
                 seq_len=2048, rope_theta=10000.0, rms_eps=1e-5,
                 position_embedding="rope", tie_embeddings=False,
                 num_experts=None, moe_k=2, moe_capacity_factor=2.0,
                 moe_aux_coeff=0.01, ep_axis=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.intermediate_size = intermediate_size
        self.seq_len = seq_len
        self.rope_theta = rope_theta
        self.rms_eps = rms_eps
        assert position_embedding in ("rope", "alibi")
        assert hidden_size % num_heads == 0, (hidden_size, num_heads)
        if position_embedding == "rope":
            # rotate_half pairs dimensions: an odd head_dim silently
            # broadcasts the tables to the wrong width downstream
            assert (hidden_size // num_heads) % 2 == 0, (
                f"RoPE needs an even head_dim; got "
                f"{hidden_size // num_heads} (hidden {hidden_size}, "
                f"heads {num_heads})")
        self.position_embedding = position_embedding
        self.tie_embeddings = tie_embeddings
        # num_experts turns each block's FFN into a top-k sparse-MoE of
        # SwiGLU experts (Mixtral-style; the reference's MoE tier is a
        # plain transformer, examples/moe — this composes it with Llama)
        self.num_experts = num_experts
        self.moe_k = moe_k
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_aux_coeff = moe_aux_coeff
        self.ep_axis = ep_axis


# published shapes (match the reference's meta_configs/hf_configs)
LLAMA_CONFIGS = {
    "llama-7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     intermediate_size=11008),
    "llama-13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                      intermediate_size=13824),
    "llama-30b": dict(hidden_size=6656, num_layers=60, num_heads=52,
                      intermediate_size=17920),
    # llama3-style GQA shape
    "llama3-8b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                      num_kv_heads=8, intermediate_size=14336,
                      vocab_size=128256, rope_theta=500000.0),
    # GQA shapes of the Mistral family (sliding-window attention not
    # modeled; full causal within seq_len)
    "mistral-7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                       num_kv_heads=8, intermediate_size=14336,
                       vocab_size=32000),
    # moe_capacity_factor = E/k: the no-drop point Mixtral parity needs
    "mixtral-8x7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                         num_kv_heads=8, intermediate_size=14336,
                         vocab_size=32000, num_experts=8, moe_k=2,
                         moe_capacity_factor=4.0),
    # reference models/baichuan: 7B is rope, 13B is alibi
    "baichuan-7b": dict(vocab_size=64000, hidden_size=4096, num_layers=32,
                        num_heads=32, intermediate_size=11008),
    "baichuan-13b": dict(vocab_size=64000, hidden_size=5120, num_layers=40,
                         num_heads=40, intermediate_size=13696,
                         position_embedding="alibi"),
}


class LlamaMLP(BaseLayer):
    """SwiGLU: down(silu(gate(x)) * up(x)) (HF LlamaMLP semantics).

    Names follow the TP contract — gate/up are column-parallel, the down
    projection is `_out` (row-parallel) so MegatronLM.annotate shards it
    without model-specific rules.
    """

    def __init__(self, hidden_size, intermediate_size, name):
        self.gate = Linear(hidden_size, intermediate_size, bias=False,
                           name=f"{name}_gate")
        self.up = Linear(hidden_size, intermediate_size, bias=False,
                         name=f"{name}_up")
        self.down = Linear(intermediate_size, hidden_size, bias=False,
                           name=f"{name}_out")

    def __call__(self, x):
        return self.down(silu_op(self.gate(x)) * self.up(x))


class LlamaDecoderLayer(BaseLayer):
    def __init__(self, config, name):
        c = config
        self.attn = MultiHeadAttention(
            c.hidden_size, c.num_heads, sequence_length=c.seq_len,
            causal_mask=True, num_kv_heads=c.num_kv_heads,
            rope_theta=(c.rope_theta
                        if c.position_embedding == "rope" else None),
            alibi=c.position_embedding == "alibi", bias=False,
            name=f"{name}_attn")
        if c.num_experts:
            from ..layers.moe import MoELayer
            self.mlp = MoELayer(c.hidden_size, c.intermediate_size,
                                num_experts=c.num_experts, k=c.moe_k,
                                capacity_factor=c.moe_capacity_factor,
                                expert_act="swiglu", ep_axis=c.ep_axis,
                                name=f"{name}_moe")
        else:
            self.mlp = LlamaMLP(c.hidden_size, c.intermediate_size,
                                name=f"{name}_mlp")
        self.input_norm = RMSNorm(c.hidden_size, eps=c.rms_eps,
                                  name=f"{name}_input_norm")
        self.post_norm = RMSNorm(c.hidden_size, eps=c.rms_eps,
                                 name=f"{name}_post_norm")

    def __call__(self, x, seq_len=None):
        a_in = self.input_norm(x)
        x = x + self.attn(a_in, a_in, a_in, seq_len=seq_len)
        return x + self.mlp(self.post_norm(x))


class LlamaModel:
    @scoped_init
    def __init__(self, config, name="llama", pipeline_stages=None):
        c = config
        self.config = c
        self.pipeline_stages = pipeline_stages
        self.embed = Embedding(c.vocab_size, c.hidden_size,
                               initializer=init.normal(0.0, 0.02),
                               name=f"{name}_embed")
        self.layers = [LlamaDecoderLayer(c, name=f"{name}_layer{i}")
                       for i in range(c.num_layers)]
        self.norm = RMSNorm(c.hidden_size, eps=c.rms_eps,
                            name=f"{name}_norm")

    def _scope(self, layer_idx=None):
        S = self.pipeline_stages
        if not S:
            return nullcontext()
        if layer_idx is None:
            return stage(0)
        bounds = np.array_split(np.arange(len(self.layers)), S)
        for s, chunk in enumerate(bounds):
            if layer_idx in chunk:
                return stage(s)
        return stage(S - 1)

    def __call__(self, input_ids):
        with self._scope():
            x = self.embed(input_ids)
        for i, layer in enumerate(self.layers):
            with self._scope(i):
                x = layer(x, seq_len=self.config.seq_len)
        with (stage(self.pipeline_stages - 1) if self.pipeline_stages
              else nullcontext()):
            return self.norm(x)


class LlamaForCausalLM:
    @scoped_init
    def __init__(self, config, name="llama", pipeline_stages=None):
        self.model = LlamaModel(config, name=name,
                                pipeline_stages=pipeline_stages)
        self.config = config
        with (stage(pipeline_stages - 1) if pipeline_stages
              else nullcontext()):
            self.lm_head = (None if config.tie_embeddings else
                            Linear(config.hidden_size, config.vocab_size,
                                   bias=False,
                                   initializer=init.normal(0.0, 0.02),
                                   name=f"{name}_lm_head"))

    def __call__(self, input_ids):
        h = self.model(input_ids)
        h = array_reshape_op(h, output_shape=(-1, self.config.hidden_size))
        if self.lm_head is None:
            return matmul_op(h, self.model.embed.weight, trans_B=True)
        return self.lm_head(h)

    def loss(self, input_ids, labels):
        """labels: [B, S] next-token ids with -1 at ignored positions
        (caller shifts, matching GPTLMHeadModel's convention)."""
        logits = self(input_ids)
        flat = array_reshape_op(labels, output_shape=(-1,))
        ce = softmax_cross_entropy_sparse_op(logits, flat, ignored_index=-1)
        loss = MaskedMeanOp(ce, flat)
        if self.config.num_experts:
            for layer in self.model.layers:
                loss = loss + self.config.moe_aux_coeff \
                    * layer.mlp.aux_loss()
        return loss


def BaichuanForCausalLM(config, name="baichuan", pipeline_stages=None):
    """The Baichuan family is the Llama architecture with its own vocab
    and (for 13B) ALiBi positions — config-level, not code-level, variants
    (reference models/baichuan/BaiChuanModel_sequential.py)."""
    return LlamaForCausalLM(config, name=name,
                            pipeline_stages=pipeline_stages)
