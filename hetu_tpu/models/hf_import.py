"""Import huggingface BERT weights into a hetu_tpu BertModel.

The reference's migration story for pretrained weights is its ONNX bridge
plus per-example conversion scripts (examples/nlp/bert load paths); for
modern checkpoints the lingua franca is huggingface.  This mapping is
validated bit-tight (5e-4) by tests/test_torch_parity.py.

Usage:
    model = BertModel(cfg, name="bert")
    ex = ht.Executor([...])
    load_hf_bert_weights(ex, model, hf_state_dict, name="bert")
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _put(params, name, value):
    if name not in params:
        raise KeyError(f"no variable {name!r} in executor params")
    value = np.asarray(value)
    if tuple(params[name].shape) != tuple(value.shape):
        raise ValueError(f"{name}: shape {params[name].shape} vs "
                         f"checkpoint {value.shape}")
    params[name] = jnp.asarray(value, dtype=params[name].dtype)


def load_hf_bert_weights(executor, model, state_dict, name="bert"):
    """Copy a transformers.BertModel state_dict into the executor.

    ``state_dict`` values may be torch tensors or numpy arrays.  torch
    Linear stores (out, in); our linear computes x @ w, so weights are
    transposed on the way in.
    """
    sd = {}
    for k, v in state_dict.items():
        sd[k] = v.detach().cpu().numpy() if hasattr(v, "detach") else \
            np.asarray(v)
    p = executor.params
    e = f"{name}_embeddings"
    _put(p, f"{e}_word_table", sd["embeddings.word_embeddings.weight"])
    _put(p, f"{e}_position", sd["embeddings.position_embeddings.weight"])
    _put(p, f"{e}_tok_type_table",
         sd["embeddings.token_type_embeddings.weight"])
    _put(p, f"{e}_ln_scale", sd["embeddings.LayerNorm.weight"])
    _put(p, f"{e}_ln_bias", sd["embeddings.LayerNorm.bias"])
    for i in range(model.config.num_hidden_layers):
        hf = f"encoder.layer.{i}."
        our = f"{name}_layer{i}"
        for proj, hname in (("q", "attention.self.query"),
                            ("k", "attention.self.key"),
                            ("v", "attention.self.value"),
                            ("out", "attention.output.dense")):
            _put(p, f"{our}_attn_{proj}_weight",
                 sd[hf + hname + ".weight"].T)
            _put(p, f"{our}_attn_{proj}_bias", sd[hf + hname + ".bias"])
        _put(p, f"{our}_ln1_scale",
             sd[hf + "attention.output.LayerNorm.weight"])
        _put(p, f"{our}_ln1_bias",
             sd[hf + "attention.output.LayerNorm.bias"])
        _put(p, f"{our}_ffn_in_weight",
             sd[hf + "intermediate.dense.weight"].T)
        _put(p, f"{our}_ffn_in_bias", sd[hf + "intermediate.dense.bias"])
        _put(p, f"{our}_ffn_out_weight", sd[hf + "output.dense.weight"].T)
        _put(p, f"{our}_ffn_out_bias", sd[hf + "output.dense.bias"])
        _put(p, f"{our}_ln2_scale", sd[hf + "output.LayerNorm.weight"])
        _put(p, f"{our}_ln2_bias", sd[hf + "output.LayerNorm.bias"])
    if "pooler.dense.weight" in sd:
        _put(p, f"{name}_pooler_weight", sd["pooler.dense.weight"].T)
        _put(p, f"{name}_pooler_bias", sd["pooler.dense.bias"])
    else:
        import warnings
        warnings.warn(
            f"checkpoint has no pooler weights; {name}'s pooler stays "
            f"randomly initialized (checkpoint saved with "
            f"add_pooling_layer=False?)", stacklevel=2)
    return executor


def load_hf_gpt2_weights(executor, model, state_dict, name="gpt"):
    """Copy a transformers.GPT2Model state_dict into a GPTModel.

    GPT-2 convs (Conv1D) already store (in, out) — no transpose.  Works
    when the architectures align (pre-LN blocks, learned positions).
    """
    sd = {}
    for k, v in state_dict.items():
        sd[k] = v.detach().cpu().numpy() if hasattr(v, "detach") else \
            np.asarray(v)
    p = executor.params
    H = model.config.hidden_size
    _put(p, f"{name}_wte_table", sd["wte.weight"])
    # our learned positions cover seq_len rows; HF ships max_positions
    _put(p, f"{name}_wpe", sd["wpe.weight"][:model.config.seq_len])
    for i in range(model.config.num_layers):
        hf = f"h.{i}."
        our = f"{name}_h{i}"
        qkv_w = sd[hf + "attn.c_attn.weight"]          # (H, 3H)
        qkv_b = sd[hf + "attn.c_attn.bias"]
        for j, proj in enumerate(("q", "k", "v")):
            _put(p, f"{our}_attn_{proj}_weight",
                 qkv_w[:, j * H:(j + 1) * H])
            _put(p, f"{our}_attn_{proj}_bias", qkv_b[j * H:(j + 1) * H])
        _put(p, f"{our}_attn_out_weight", sd[hf + "attn.c_proj.weight"])
        _put(p, f"{our}_attn_out_bias", sd[hf + "attn.c_proj.bias"])
        _put(p, f"{our}_ln1_scale", sd[hf + "ln_1.weight"])
        _put(p, f"{our}_ln1_bias", sd[hf + "ln_1.bias"])
        _put(p, f"{our}_ffn_in_weight", sd[hf + "mlp.c_fc.weight"])
        _put(p, f"{our}_ffn_in_bias", sd[hf + "mlp.c_fc.bias"])
        _put(p, f"{our}_ffn_out_weight", sd[hf + "mlp.c_proj.weight"])
        _put(p, f"{our}_ffn_out_bias", sd[hf + "mlp.c_proj.bias"])
        _put(p, f"{our}_ln2_scale", sd[hf + "ln_2.weight"])
        _put(p, f"{our}_ln2_bias", sd[hf + "ln_2.bias"])
    _put(p, f"{name}_ln_f_scale", sd["ln_f.weight"])
    _put(p, f"{name}_ln_f_bias", sd["ln_f.bias"])
    return executor


def load_hf_llama_weights(executor, model, state_dict, name="llama"):
    """Copy a transformers Llama-family state_dict into a
    LlamaForCausalLM.  Baichuan checkpoints also fit: their fused
    ``self_attn.W_pack`` projection is split into equal q/k/v thirds
    (Baichuan has no GQA, so the thirds are all hidden-sized).

    Accepts state_dicts with or without the ``model.`` prefix.  Our
    rotary op follows HF's rotate_half convention, so q/k come over
    unpermuted.
    """
    sd = {}
    for k, v in state_dict.items():
        v = v.detach().cpu().numpy() if hasattr(v, "detach") else \
            np.asarray(v)
        sd[k[6:] if k.startswith("model.") else k] = v
    p = executor.params
    cfg = model.config
    _put(p, f"{name}_embed_table", sd["embed_tokens.weight"])
    for i in range(cfg.num_layers):
        hf = f"layers.{i}."
        our = f"{name}_layer{i}"
        if hf + "self_attn.W_pack.weight" in sd:   # Baichuan fused qkv
            wp = sd[hf + "self_attn.W_pack.weight"]       # (3H, H)
            h3 = wp.shape[0] // 3
            for j, proj in enumerate(("q", "k", "v")):
                sd[hf + f"self_attn.{proj}_proj.weight"] = \
                    wp[j * h3:(j + 1) * h3]
        for proj, hname in (("q", "self_attn.q_proj"),
                            ("k", "self_attn.k_proj"),
                            ("v", "self_attn.v_proj"),
                            ("out", "self_attn.o_proj")):
            _put(p, f"{our}_attn_{proj}_weight", sd[hf + hname + ".weight"].T)
        _put(p, f"{our}_mlp_gate_weight", sd[hf + "mlp.gate_proj.weight"].T)
        _put(p, f"{our}_mlp_up_weight", sd[hf + "mlp.up_proj.weight"].T)
        _put(p, f"{our}_mlp_out_weight", sd[hf + "mlp.down_proj.weight"].T)
        _put(p, f"{our}_input_norm_scale", sd[hf + "input_layernorm.weight"])
        _put(p, f"{our}_post_norm_scale",
             sd[hf + "post_attention_layernorm.weight"])
    _put(p, f"{name}_norm_scale", sd["norm.weight"])
    if model.lm_head is not None:
        if "lm_head.weight" in sd:
            _put(p, f"{name}_lm_head_weight", sd["lm_head.weight"].T)
        else:  # tied checkpoint into an untied model
            _put(p, f"{name}_lm_head_weight", sd["embed_tokens.weight"].T)
    elif ("lm_head.weight" in sd
          and not np.array_equal(sd["lm_head.weight"],
                                 sd["embed_tokens.weight"])):
        raise ValueError(
            "checkpoint has an untied lm_head.weight but the model was "
            "built with tie_embeddings=True — its logits would silently "
            "diverge; rebuild with tie_embeddings=False")
    return executor


def export_hf_llama_weights(executor, model, name="llama"):
    """Inverse of ``load_hf_llama_weights``: an executor's Llama params as
    a transformers-layout state_dict of numpy arrays (``model.`` prefix,
    (out, in) weight orientation) — loadable by
    transformers.LlamaForCausalLM.load_state_dict after torch.from_numpy.
    Round-trip interop is the reference's ONNX-bridge role for modern
    checkpoints (tests/test_torch_parity.py proves both directions)."""
    p = executor.params
    cfg = model.config

    def get(n):
        return np.asarray(p[n])

    sd = {"model.embed_tokens.weight": get(f"{name}_embed_table"),
          "model.norm.weight": get(f"{name}_norm_scale")}
    for i in range(cfg.num_layers):
        hf = f"model.layers.{i}."
        our = f"{name}_layer{i}"
        for proj, hname in (("q", "self_attn.q_proj"),
                            ("k", "self_attn.k_proj"),
                            ("v", "self_attn.v_proj"),
                            ("out", "self_attn.o_proj")):
            sd[hf + hname + ".weight"] = get(f"{our}_attn_{proj}_weight").T
        sd[hf + "mlp.gate_proj.weight"] = get(f"{our}_mlp_gate_weight").T
        sd[hf + "mlp.up_proj.weight"] = get(f"{our}_mlp_up_weight").T
        sd[hf + "mlp.down_proj.weight"] = get(f"{our}_mlp_out_weight").T
        sd[hf + "input_layernorm.weight"] = get(f"{our}_input_norm_scale")
        sd[hf + "post_attention_layernorm.weight"] = \
            get(f"{our}_post_norm_scale")
    if model.lm_head is not None:
        sd["lm_head.weight"] = get(f"{name}_lm_head_weight").T
    else:
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    return sd


def load_hf_mixtral_weights(executor, model, state_dict, name="llama"):
    """Copy a transformers.MixtralForCausalLM state_dict into a
    LlamaForCausalLM built with ``num_experts`` (SwiGLU sparse-MoE
    blocks).  Router gate -> TopKGate wg; per-expert w1/w3/w2 stack into
    the MoELayer's [E, H, F]/[E, F, H] tensors.  Gating math matches:
    top-2 renormalization of full-softmax probs equals Mixtral's softmax
    over the top-2 logits, and capacity_factor >= E/k drops nothing."""
    sd = {}
    for k, v in state_dict.items():
        v = v.detach().cpu().numpy() if hasattr(v, "detach") else \
            np.asarray(v)
        sd[k[6:] if k.startswith("model.") else k] = v
    p = executor.params
    cfg = model.config
    E = cfg.num_experts
    _put(p, f"{name}_embed_table", sd["embed_tokens.weight"])
    for i in range(cfg.num_layers):
        hf = f"layers.{i}."
        our = f"{name}_layer{i}"
        for proj, hname in (("q", "self_attn.q_proj"),
                            ("k", "self_attn.k_proj"),
                            ("v", "self_attn.v_proj"),
                            ("out", "self_attn.o_proj")):
            _put(p, f"{our}_attn_{proj}_weight", sd[hf + hname + ".weight"].T)
        moe = hf + "block_sparse_moe."
        # variable names come from the layer object (fresh_name may have
        # suffixed the gate), not from string reconstruction
        mlp = model.model.layers[i].mlp
        _put(p, mlp.gate.wg.name, sd[moe + "gate.weight"].T)   # [H, E]
        _put(p, mlp.w1.name, np.stack(
            [sd[moe + f"experts.{j}.w1.weight"].T for j in range(E)]))
        _put(p, mlp.w3.name, np.stack(
            [sd[moe + f"experts.{j}.w3.weight"].T for j in range(E)]))
        _put(p, mlp.w2.name, np.stack(
            [sd[moe + f"experts.{j}.w2.weight"].T for j in range(E)]))
        _put(p, f"{our}_input_norm_scale", sd[hf + "input_layernorm.weight"])
        _put(p, f"{our}_post_norm_scale",
             sd[hf + "post_attention_layernorm.weight"])
    _put(p, f"{name}_norm_scale", sd["norm.weight"])
    if model.lm_head is not None:
        _put(p, f"{name}_lm_head_weight", sd["lm_head.weight"].T)
    return executor
