"""MLP / simple CNN (reference: examples/cnn/models/{MLP,CNN,LeNet}.py)."""

from __future__ import annotations

from ..graph.node import scoped_init

from ..layers import Linear, Conv2d, MaxPool2d, Sequence, Relu, Reshape
from ..ops import relu_op, array_reshape_op, flatten_op


class MLP:
    @scoped_init
    def __init__(self, dims=(784, 256, 256, 10), name="mlp"):
        self.linears = [Linear(dims[i], dims[i + 1], name=f"{name}_fc{i}")
                        for i in range(len(dims) - 1)]

    def __call__(self, x):
        for i, l in enumerate(self.linears):
            x = l(x)
            if i < len(self.linears) - 1:
                x = relu_op(x)
        return x


class LeNet:
    @scoped_init
    def __init__(self, num_classes=10, name="lenet"):
        self.conv1 = Conv2d(1, 6, 5, padding=2, name=f"{name}_c1")
        self.pool = MaxPool2d(2)
        self.conv2 = Conv2d(6, 16, 5, name=f"{name}_c2")
        self.fc1 = Linear(16 * 5 * 5, 120, name=f"{name}_f1")
        self.fc2 = Linear(120, 84, name=f"{name}_f2")
        self.fc3 = Linear(84, num_classes, name=f"{name}_f3")

    def __call__(self, x):
        x = self.pool(relu_op(self.conv1(x)))
        x = self.pool(relu_op(self.conv2(x)))
        x = flatten_op(x)
        x = relu_op(self.fc1(x))
        x = relu_op(self.fc2(x))
        return self.fc3(x)
