"""Shared pieces of the KV-cache decoders (llama_decode / gpt_decode /
transformer_decode) and their executor-facing wrappers."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def param_prefix(executor, suffix):
    """Infer a model's parameter-name prefix from an Executor's params by
    the unique variable ending in ``suffix`` (e.g. ``_embed_table``).
    The three decode wrappers used to each hand-roll this lookup."""
    try:
        return next(k for k in executor.params
                    if k.endswith(suffix)).rsplit(suffix, 1)[0]
    except StopIteration:
        raise KeyError(
            f"no executor param ends with {suffix!r} — pass name= "
            "explicitly") from None


def executor_generate(fn, executor, arrays, seed=0):
    """Shared tail of every ``*_generate`` wrapper: call the jitted
    decode program on the executor's params with a seeded PRNG key and
    materialize the tokens to numpy."""
    return np.asarray(fn(executor.params, *arrays, jax.random.key(seed)))


def pad_prompts(prompts, pad_to=None, pad_id=0):
    """Right-pad variable-length prompts into one [B, P] int32 batch.

    Returns ``(ids, lengths)`` with ``lengths`` the true prompt lengths.
    ``pad_to`` fixes P (serving's static prefill bucket); by default P is
    the longest prompt."""
    lens = np.asarray([len(np.asarray(p).reshape(-1)) for p in prompts],
                      np.int32)
    if lens.size and lens.min() < 1:
        raise ValueError("empty prompt")
    p_len = int(pad_to) if pad_to is not None else int(lens.max())
    if lens.size and int(lens.max()) > p_len:
        raise ValueError(
            f"prompt of length {int(lens.max())} exceeds pad_to={p_len}")
    ids = np.full((len(prompts), p_len), pad_id, np.int32)
    for i, p in enumerate(prompts):
        ids[i, :lens[i]] = np.asarray(p).reshape(-1)
    return ids, lens


def layer_norm(x, g, b, eps=1e-5):
    """fp32-moments LayerNorm shared by the hand-written decoders."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g + b)


def make_picker(temperature, top_k):
    """Token selection for decode: greedy argmax at temperature<=0, else
    categorical over softmax(logits/temperature) restricted to the top_k
    largest logits."""

    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        lg = logits.astype(jnp.float32) / temperature
        if top_k > 0:
            kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        return jax.random.categorical(key, lg, axis=-1)

    return pick


def make_slot_picker():
    """Per-lane token selection from OPERANDS instead of closure
    constants: ``pick(logits [S, V], temps [S], top_ks [S], seeds [S],
    consumed [S])`` samples each lane under its own temperature / top_k
    / seed without recompiling per sampling signature (the paged
    engine's per-request sampling).

    Determinism contract: lane keys derive from ``fold_in(fold_in(
    key(0), seed), consumed)`` where ``consumed`` counts the tokens the
    request has produced so far (prompt length at prefill, position + 1
    at decode) — a function of the REQUEST's seed and progress only,
    never of the slot index, co-tenants, or engine instance.  A sampled
    stream is therefore reproducible at a fixed seed and continues
    bit-exactly after a failover replay onto another replica.

    Greedy lanes (temperature <= 0) use the identical ``jnp.argmax`` the
    closure picker uses, preserving bitwise parity with the slot twin.
    An all-greedy batch — the common serving case — skips the whole
    sort/sample branch at RUNTIME via ``lax.cond`` (both branches are
    traced once; only the taken one executes), so per-request sampling
    support costs greedy-only workloads nothing per step.
    """

    def pick(logits, temps, top_ks, seeds, consumed):
        greedy = jnp.argmax(logits, axis=-1)

        def sample(_):
            lg = logits.astype(jnp.float32) / jnp.maximum(
                temps, 1e-6)[:, None]
            v = lg.shape[-1]
            # per-lane top-k via a full descending sort: lane i keeps
            # logits >= the top_ks[i]-th largest (top_ks == 0 keeps
            # everything)
            srt = jnp.sort(lg, axis=-1)[:, ::-1]
            kth_idx = jnp.clip(top_ks - 1, 0, v - 1)
            kth = jnp.take_along_axis(srt, kth_idx[:, None], axis=-1)
            lg = jnp.where((top_ks[:, None] > 0) & (lg < kth),
                           -jnp.inf, lg)
            base = jax.random.key(0)

            def lane(row, seed, step):
                k = jax.random.fold_in(jax.random.fold_in(base, seed),
                                       step)
                return jax.random.categorical(k, row, axis=-1)

            sampled = jax.vmap(lane)(lg, seeds, consumed)
            return jnp.where(temps <= 0.0, greedy, sampled)

        return jax.lax.cond(jnp.any(temps > 0.0), sample,
                            lambda _: greedy, None)

    return pick


def make_gather(mesh, quant_dtype=None):
    """The tensor-parallel replicate-back hook for ``make_block``'s
    ``gather=``: constrain an activation to fully-replicated on
    ``mesh`` so GSPMD inserts an all-gather (byte movement — exact)
    instead of a psum of partial dot products (reduction reordering —
    would break the sharded engine's bitwise-parity oracle).  Works
    under ``jax.vmap``: the batched dim joins the spec as replicated.

    ``quant_dtype`` ('int8' | 'fp8', EQuARX-style) moves the gather's
    bytes through the shared block codec instead: the activation is
    quantized per shard-width block BEFORE the replication constraint
    — so each chip's local block gets its own absmax scale and the
    all-gather transports 1-byte codes plus a small f32 scale vector —
    and dequantized right after.  This trades the bitwise-parity oracle
    for a bounded divergence (tests/test_sharded_serving.py carries the
    relaxed twin), which is why it defaults OFF: the unquantized path
    is byte-identical to what this function always built."""
    from jax.sharding import NamedSharding, PartitionSpec
    rep = NamedSharding(mesh, PartitionSpec())
    if quant_dtype is None:

        def gather(x):
            return jax.lax.with_sharding_constraint(x, rep)

        return gather

    from ..ops import quant as _quant
    _quant.code_dtype(quant_dtype)        # fail fast on a bad codec
    tp = 1
    for size in mesh.shape.values():
        tp *= int(size)

    def gather(x):
        d = x.shape[-1]
        # one block per shard when the width divides; otherwise a
        # whole-axis block (still quantized transport, coarser scale)
        block = d // tp if tp > 1 and d % tp == 0 else None
        codes, scales = _quant.quantize_blocks(x, block=block,
                                               dtype=quant_dtype)
        codes = jax.lax.with_sharding_constraint(codes, rep)
        scales = jax.lax.with_sharding_constraint(scales, rep)
        return _quant.dequantize_blocks(codes, scales)

    return gather


def make_attend(head_dim, n_rep=1):
    """Masked cache attention: q [B, H, Sq, D] against cached keys/vals
    [B, KV, T, D] (kv heads broadcast n_rep-fold for GQA), with an
    additive position mask [Sq, T]."""

    def attend(q, keys, vals, pos_mask):
        if n_rep > 1:
            b, kv, t, d = keys.shape
            keys = jnp.broadcast_to(keys[:, :, None],
                                    (b, kv, n_rep, t, d)).reshape(
                b, kv * n_rep, t, d)
            vals = jnp.broadcast_to(vals[:, :, None],
                                    (b, kv, n_rep, t, d)).reshape(
                b, kv * n_rep, t, d)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, keys,
                       preferred_element_type=jnp.float32) \
            / np.sqrt(head_dim)
        s = jnp.where(pos_mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(vals.dtype), vals,
                          preferred_element_type=jnp.float32
                          ).astype(vals.dtype)

    return attend


def assemble(prompt_ids, first, last, toks, max_new):
    """[prompt | generated] given the scan outputs (first token computed
    at prefill, `toks` the scanned tokens, `last` the final carry)."""
    del first
    gen = jnp.concatenate(
        [toks.transpose(1, 0), last], axis=1) if max_new > 1 else last
    return jnp.concatenate([prompt_ids, gen], axis=1)
