"""Shared pieces of the KV-cache decoders (llama_decode / gpt_decode)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def layer_norm(x, g, b, eps=1e-5):
    """fp32-moments LayerNorm shared by the hand-written decoders."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g + b)


def make_picker(temperature, top_k):
    """Token selection for decode: greedy argmax at temperature<=0, else
    categorical over softmax(logits/temperature) restricted to the top_k
    largest logits."""

    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        lg = logits.astype(jnp.float32) / temperature
        if top_k > 0:
            kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        return jax.random.categorical(key, lg, axis=-1)

    return pick


def make_attend(head_dim, n_rep=1):
    """Masked cache attention: q [B, H, Sq, D] against cached keys/vals
    [B, KV, T, D] (kv heads broadcast n_rep-fold for GQA), with an
    additive position mask [Sq, T]."""

    def attend(q, keys, vals, pos_mask):
        if n_rep > 1:
            b, kv, t, d = keys.shape
            keys = jnp.broadcast_to(keys[:, :, None],
                                    (b, kv, n_rep, t, d)).reshape(
                b, kv * n_rep, t, d)
            vals = jnp.broadcast_to(vals[:, :, None],
                                    (b, kv, n_rep, t, d)).reshape(
                b, kv * n_rep, t, d)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, keys,
                       preferred_element_type=jnp.float32) \
            / np.sqrt(head_dim)
        s = jnp.where(pos_mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(vals.dtype), vals,
                          preferred_element_type=jnp.float32
                          ).astype(vals.dtype)

    return attend


def assemble(prompt_ids, first, last, toks, max_new):
    """[prompt | generated] given the scan outputs (first token computed
    at prefill, `toks` the scanned tokens, `last` the final carry)."""
    del first
    gen = jnp.concatenate(
        [toks.transpose(1, 0), last], axis=1) if max_new > 1 else last
    return jnp.concatenate([prompt_ids, gen], axis=1)
