"""Shared pieces of the KV-cache decoders (llama_decode / gpt_decode /
transformer_decode) and their executor-facing wrappers."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def param_prefix(executor, suffix):
    """Infer a model's parameter-name prefix from an Executor's params by
    the unique variable ending in ``suffix`` (e.g. ``_embed_table``).
    The three decode wrappers used to each hand-roll this lookup."""
    try:
        return next(k for k in executor.params
                    if k.endswith(suffix)).rsplit(suffix, 1)[0]
    except StopIteration:
        raise KeyError(
            f"no executor param ends with {suffix!r} — pass name= "
            "explicitly") from None


def executor_generate(fn, executor, arrays, seed=0):
    """Shared tail of every ``*_generate`` wrapper: call the jitted
    decode program on the executor's params with a seeded PRNG key and
    materialize the tokens to numpy."""
    return np.asarray(fn(executor.params, *arrays, jax.random.key(seed)))


def pad_prompts(prompts, pad_to=None, pad_id=0):
    """Right-pad variable-length prompts into one [B, P] int32 batch.

    Returns ``(ids, lengths)`` with ``lengths`` the true prompt lengths.
    ``pad_to`` fixes P (serving's static prefill bucket); by default P is
    the longest prompt."""
    lens = np.asarray([len(np.asarray(p).reshape(-1)) for p in prompts],
                      np.int32)
    if lens.size and lens.min() < 1:
        raise ValueError("empty prompt")
    p_len = int(pad_to) if pad_to is not None else int(lens.max())
    if lens.size and int(lens.max()) > p_len:
        raise ValueError(
            f"prompt of length {int(lens.max())} exceeds pad_to={p_len}")
    ids = np.full((len(prompts), p_len), pad_id, np.int32)
    for i, p in enumerate(prompts):
        ids[i, :lens[i]] = np.asarray(p).reshape(-1)
    return ids, lens


def layer_norm(x, g, b, eps=1e-5):
    """fp32-moments LayerNorm shared by the hand-written decoders."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g + b)


def make_picker(temperature, top_k):
    """Token selection for decode: greedy argmax at temperature<=0, else
    categorical over softmax(logits/temperature) restricted to the top_k
    largest logits."""

    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        lg = logits.astype(jnp.float32) / temperature
        if top_k > 0:
            kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        return jax.random.categorical(key, lg, axis=-1)

    return pick


def make_attend(head_dim, n_rep=1):
    """Masked cache attention: q [B, H, Sq, D] against cached keys/vals
    [B, KV, T, D] (kv heads broadcast n_rep-fold for GQA), with an
    additive position mask [Sq, T]."""

    def attend(q, keys, vals, pos_mask):
        if n_rep > 1:
            b, kv, t, d = keys.shape
            keys = jnp.broadcast_to(keys[:, :, None],
                                    (b, kv, n_rep, t, d)).reshape(
                b, kv * n_rep, t, d)
            vals = jnp.broadcast_to(vals[:, :, None],
                                    (b, kv, n_rep, t, d)).reshape(
                b, kv * n_rep, t, d)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, keys,
                       preferred_element_type=jnp.float32) \
            / np.sqrt(head_dim)
        s = jnp.where(pos_mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(vals.dtype), vals,
                          preferred_element_type=jnp.float32
                          ).astype(vals.dtype)

    return attend


def assemble(prompt_ids, first, last, toks, max_new):
    """[prompt | generated] given the scan outputs (first token computed
    at prefill, `toks` the scanned tokens, `last` the final carry)."""
    del first
    gen = jnp.concatenate(
        [toks.transpose(1, 0), last], axis=1) if max_new > 1 else last
    return jnp.concatenate([prompt_ids, gen], axis=1)
