"""BERT (reference: examples/nlp/bert/hetu_bert.py — embeddings + encoder
stack + MLM/NSP heads; the DP-8 throughput north-star model).

Graph-level model: __call__ builds nodes from id placeholders.  The input
contract matches the reference: input_ids/token_type_ids/attention_mask of
shape [B, S]; attention_mask is converted to an additive [B,1,1,S] bias.
"""

from __future__ import annotations

import numpy as np

from ..graph.node import Op, VariableOp, scoped_init
from .. import initializers as init
from ..layers import (Linear, LayerNorm, Embedding, TransformerLayer,
                      fresh_name)
from ..ops import (array_reshape_op, dropout_op, gelu_op, tanh_op,
                   embedding_lookup_op, matmul_op, broadcastto_op,
                   softmax_cross_entropy_sparse_op, reduce_mean_op, slice_op)


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1, seq_len=128,
                 mlm_bucket_frac=0.25):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.seq_len = seq_len
        # Fraction of tokens the MLM head's masked-position bucket holds.
        # Must exceed the masking rate (0.25 covers the standard 15%
        # recipe); batches that mask more positions than the bucket trip a
        # runtime overflow warning in MaskedSelectLabelsOp and the excess
        # tokens are excluded from the loss.  None = dense full-position
        # head (use for span/40% masking recipes).
        self.mlm_bucket_frac = mlm_bucket_frac


class AttentionMaskOp(Op):
    """[B, S] 0/1 mask -> additive [B, 1, 1, S] bias (reference
    examples/nlp/bert/hetu_bert.py extended_attention_mask)."""

    def _compute(self, input_vals, ctx):
        import jax.numpy as jnp
        (m,) = input_vals
        return ((1.0 - m.astype(jnp.float32))
                * -10000.0)[:, None, None, :]


class PositionIdsOp(Op):
    """Broadcast [S] position embedding rows over the batch of x."""

    def __init__(self, table, x, seq_len):
        super().__init__(table, x, name="position_embed")
        self.seq_len = seq_len

    def _compute(self, input_vals, ctx):
        table, x = input_vals
        return table[None, :self.seq_len, :]


class BertEmbeddings:
    def __init__(self, config, name="bert_embeddings"):
        c = config
        self.word = Embedding(c.vocab_size, c.hidden_size,
                              initializer=init.normal(0.0, 0.02),
                              name=f"{name}_word")
        self.position = VariableOp(f"{name}_position",
                                   (c.max_position_embeddings, c.hidden_size),
                                   init.normal(0.0, 0.02))
        self.token_type = Embedding(c.type_vocab_size, c.hidden_size,
                                    initializer=init.normal(0.0, 0.02),
                                    name=f"{name}_tok_type")
        self.ln = LayerNorm(c.hidden_size, name=f"{name}_ln")
        self.dropout_keep = 1.0 - c.hidden_dropout_prob
        self.config = config

    def __call__(self, input_ids, token_type_ids):
        x = self.word(input_ids) + self.token_type(token_type_ids)
        x = x + PositionIdsOp(self.position, x, self.config.seq_len)
        x = self.ln(x)
        if self.dropout_keep < 1.0:
            x = dropout_op(x, keep_prob=self.dropout_keep)
        return x


class BertModel:
    @scoped_init
    def __init__(self, config, name="bert"):
        c = config
        self.config = c
        self.embeddings = BertEmbeddings(c, name=f"{name}_embeddings")
        self.encoder = [
            TransformerLayer(c.hidden_size, c.num_attention_heads,
                             c.intermediate_size, seq_len=c.seq_len,
                             dropout_rate=c.hidden_dropout_prob,
                             attn_dropout_rate=c.attention_probs_dropout_prob,
                             causal=False, pre_norm=False,
                             name=f"{name}_layer{i}")
            for i in range(c.num_hidden_layers)]
        self.pooler = Linear(c.hidden_size, c.hidden_size,
                             name=f"{name}_pooler")

    def __call__(self, input_ids, token_type_ids, attention_mask=None):
        mask = AttentionMaskOp(attention_mask) \
            if attention_mask is not None else None
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask=mask, seq_len=self.config.seq_len)
        # pooled = tanh(W @ x[:, 0])
        pooled = tanh_op(self.pooler(FirstTokenOp(x)))
        return x, pooled


class FirstTokenOp(Op):
    """[B, S, H] -> [B, H] (CLS token for the pooler)."""

    def _compute(self, input_vals, ctx):
        (x,) = input_vals
        return x[:, 0, :]


class BertForPreTraining:
    """MLM + NSP heads (reference examples/nlp/bert/hetu_bert.py)."""

    @scoped_init
    def __init__(self, config, name="bert"):
        c = config
        self.config = c
        self.bert = BertModel(config, name=name)
        self.mlm_transform = Linear(c.hidden_size, c.hidden_size,
                                    name=f"{name}_mlm_transform")
        self.mlm_ln = LayerNorm(c.hidden_size, name=f"{name}_mlm_ln")
        # decoder shares the word-embedding table (tied weights)
        self.mlm_bias = VariableOp(f"{name}_mlm_bias", (c.vocab_size,),
                                   init.zeros())
        self.nsp = Linear(c.hidden_size, 2, name=f"{name}_nsp")

    def __call__(self, input_ids, token_type_ids, attention_mask):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_ln(gelu_op(self.mlm_transform(
            array_reshape_op(seq, output_shape=(-1,
                                                self.config.hidden_size)))))
        logits = matmul_op(h, self.bert.embeddings.word.weight, trans_B=True)
        logits = logits + broadcastto_op(self.mlm_bias, logits)
        nsp_logits = self.nsp(pooled)
        return logits, nsp_logits

    def loss(self, input_ids, token_type_ids, attention_mask, mlm_labels,
             nsp_labels):
        """mlm_labels: [B*S] with -1 for unmasked; nsp_labels: [B].

        The MLM head (transform + LN + tied vocab decoder) runs only on a
        static BUCKET of masked positions (`config.mlm_bucket_frac`,
        default 0.25 of the tokens — standard masking is 0.15): unmasked
        positions contribute zero loss AND zero gradient through the head,
        so gathering first is mathematically identical while cutting the
        dominant [tokens, vocab] matmuls ~4x.  Set mlm_bucket_frac=None
        for the dense full-position head.
        """
        c = self.config
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        flat = array_reshape_op(seq, output_shape=(-1, c.hidden_size))
        frac = c.mlm_bucket_frac
        n_tokens = None
        shape = getattr(mlm_labels, "shape", None)
        if frac is not None and shape is not None and shape[0] is not None:
            n_tokens = int(shape[0])
        if n_tokens is not None:
            bucket = min(n_tokens, -(-int(n_tokens * frac) // 128) * 128)
            h_in = MaskedSelectOp(flat, mlm_labels, bucket=bucket)
            labels_in = MaskedSelectLabelsOp(mlm_labels, bucket=bucket)
        else:
            h_in, labels_in = flat, mlm_labels
        h = self.mlm_ln(gelu_op(self.mlm_transform(h_in)))
        logits = matmul_op(h, self.bert.embeddings.word.weight, trans_B=True)
        logits = logits + broadcastto_op(self.mlm_bias, logits)
        ce = softmax_cross_entropy_sparse_op(logits, labels_in,
                                             ignored_index=-1)
        mlm_loss = MaskedMeanOp(ce, labels_in)
        nsp_loss = reduce_mean_op(softmax_cross_entropy_sparse_op(
            self.nsp(pooled), nsp_labels))
        return mlm_loss + nsp_loss


class MaskedSelectOp(Op):
    """Rows of ``x`` at the first ``bucket`` positions where label >= 0
    (fill rows repeat index 0; their loss weight is zeroed downstream, so
    their gradients vanish too).  If more than ``bucket`` positions are
    masked, the excess is dropped — size the bucket above the masking
    rate."""

    def __init__(self, x, labels, bucket, name=None):
        super().__init__(x, labels, name=name)
        self.bucket = int(bucket)

    def _compute(self, input_vals, ctx):
        import jax.numpy as jnp
        x, labels = input_vals
        (pos,) = jnp.nonzero(labels.reshape(-1) >= 0, size=self.bucket,
                             fill_value=0)
        return x[pos]


class MaskedSelectLabelsOp(Op):
    """Labels gathered like MaskedSelectOp's rows, with fill slots forced
    to -1 (ignored) so downstream CE/normalization see only true masks.

    Overflowed masked positions are dropped from the loss; that is a
    silent objective change, so it is surfaced as an IN-GRAPH cumulative
    counter (a non-trainable variable the executor polls host-side every
    ``monitor_interval`` steps and warns on).  Host callbacks are NOT
    used: the platform where the headline BERT number is measured (axon
    dev-tunnel PJRT) doesn't support them, which made the previous
    callback-based warning vanish exactly where it mattered
    (VERDICT r3 item 7)."""

    def __init__(self, labels, bucket, name=None):
        name = name or fresh_name("masked_labels")
        # int32 counter: exact accumulation (an f32 total would silently
        # freeze past 2^24), and ints bypass the compute_dtype cast so
        # mixed precision never quantizes it
        self.overflow_total = VariableOp(f"{name}_overflow_total", (),
                                         init.zeros(), trainable=False,
                                         dtype=np.int32)
        self.overflow_total.monitor = (
            lambda v: None if v <= 0 else
            f"hetu_tpu: MLM bucket overflow — {int(v)} masked positions "
            "(cumulative) exceeded the bucket and were excluded from the "
            "loss.  Raise BertConfig.mlm_bucket_frac or set it to None.")
        super().__init__(labels, self.overflow_total, name=name)
        self.bucket = int(bucket)
        # opt OUT of any enclosing `with ht.remat():` scope instead of
        # tripping its stateful-op guard: the op is a cheap label gather
        # (nothing worth rematerializing) and keeping it outside the
        # checkpoint group means the counter update runs exactly once
        self.remat_scope = None

    @property
    def is_stateful(self):
        # keeps the trace-level stateful guard honest for any future
        # remat path that might capture this op
        return True

    def _compute(self, input_vals, ctx):
        import jax.numpy as jnp
        labels, total = input_vals
        labels = labels.reshape(-1)
        valid = labels >= 0
        n_valid = jnp.sum(valid)
        over = jnp.maximum(n_valid - self.bucket, 0).astype(jnp.int32)
        ctx.record_update(self.overflow_total, total + over)
        (pos,) = jnp.nonzero(valid, size=self.bucket, fill_value=0)
        live = jnp.arange(self.bucket) < n_valid
        return jnp.where(live, labels[pos], -1)


class BertForSequenceClassification:
    """Pooled-CLS classifier head for GLUE fine-tuning (reference
    examples/nlp/bert/test_glue_hetu_bert.py builds the same
    dropout(pooled) -> Linear(num_labels) head)."""

    @scoped_init
    def __init__(self, config, num_labels, name="bert"):
        self.config = config
        self.num_labels = num_labels
        self.bert = BertModel(config, name=name)
        self.dropout_keep = 1.0 - config.hidden_dropout_prob
        self.classifier = Linear(config.hidden_size, num_labels,
                                 initializer=init.normal(0.0, 0.02),
                                 name=f"{name}_classifier")

    def __call__(self, input_ids, token_type_ids, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        if self.dropout_keep < 1.0:
            pooled = dropout_op(pooled, self.dropout_keep)
        return self.classifier(pooled)

    def loss(self, input_ids, token_type_ids, attention_mask, labels):
        logits = self(input_ids, token_type_ids, attention_mask)
        return reduce_mean_op(
            softmax_cross_entropy_sparse_op(logits, labels)), logits


class MaskedMeanOp(Op):
    """Mean of per-token losses over positions with label >= 0 (the
    reference normalizes MLM loss by the masked-token count)."""

    def _compute(self, input_vals, ctx):
        import jax.numpy as jnp
        ce, labels = input_vals
        valid = (labels.reshape(-1) >= 0).astype(ce.dtype)
        return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1.0)
