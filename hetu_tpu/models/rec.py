"""NCF-family recommendation models: MF / GMF / MLP / NeuMF rating heads.

Reference: examples/rec/models/base.py:5 (RatingModel_Head: MSE + MAE
losses over a prediction computed from user/item embeddings),
mf.py:5 (MF_Head), gmf.py:6 (GMF_Head), mlp.py:6 (MLP_Head),
neumf.py:6 (NeuMF_Head), driven by examples/rec/run_compressed.py which
feeds `[B, 2]` (user, item) ids through a — possibly compressed —
embedding layer.  The heads here take the embedding output directly so
they compose with every `embed_compress` method exactly as the
reference's do; `NCFModel` is the convenience wrapper with a plain
shared table.
"""

from __future__ import annotations

from ..layers import Embedding, Linear, Sequence, fresh_name
from .. import initializers as init
from ..graph.node import scoped_init
from ..ops import (array_reshape_op, concat_op, mae_loss_op, mse_loss_op,
                   reduce_mul_op, reduce_sum_op, relu_op, slice_op)


class RatingModelHead:
    """Base rating head (reference examples/rec/models/base.py:5).

    ``__call__(embeddings, label)`` takes the looked-up (user, item)
    embeddings — shape ``[B, 2, D]`` or ``[B, 2*D]`` — and the rating
    labels ``[B]``, and returns ``(mse_loss, mae_loss, prediction)``.
    """

    def __init__(self, embed_dim):
        self.embed_dim = embed_dim

    def create_mlp(self, dims, name="mlp"):
        # reference base.py:18 create_mlp: xavier-normal Linears with relu
        return Sequence(*[
            Linear(int(n), int(m), initializer=init.xavier_normal(),
                   activation=relu_op, name=f"{name}_{i * 2}")
            for i, (n, m) in enumerate(zip(dims[:-1], dims[1:]))])

    def __call__(self, embeddings, label):
        raise NotImplementedError

    def output(self, prediction, label):
        # reference base.py:40: MSE is the training loss, MAE reported
        return (mse_loss_op(prediction, label),
                mae_loss_op(prediction, label), prediction)


class MFHead(RatingModelHead):
    """Matrix factorization: dot(user, item) (reference mf.py:5)."""

    def __call__(self, embeddings, label):
        embeddings = array_reshape_op(
            embeddings, output_shape=(-1, 2, self.embed_dim))
        prediction = reduce_sum_op(
            reduce_mul_op(embeddings, axes=(1,)), axes=(-1,))
        return self.output(prediction, label)


class GMFHead(RatingModelHead):
    """Generalized MF: learned combination of the elementwise product
    (reference gmf.py:6)."""

    def __init__(self, embed_dim, name=None):
        super().__init__(embed_dim)
        name = fresh_name(name or "gmf")
        self.predict_layer = Linear(embed_dim, 1,
                                    initializer=init.xavier_normal(),
                                    name=f"{name}_predict")

    def __call__(self, embeddings, label):
        embeddings = array_reshape_op(
            embeddings, output_shape=(-1, 2, self.embed_dim))
        interaction = reduce_mul_op(embeddings, axes=(1,))
        prediction = array_reshape_op(self.predict_layer(interaction),
                                      output_shape=(-1,))
        return self.output(prediction, label)


class MLPHead(RatingModelHead):
    """MLP over concatenated embeddings (reference mlp.py:6): with
    ``f = D // 4`` the tower is ``[8f, 4f, 2f, f]`` then ``f -> 1``."""

    def __init__(self, embed_dim, name=None):
        if embed_dim % 4:
            raise ValueError("MLPHead needs embed_dim % 4 == 0 "
                             f"(got {embed_dim})")
        super().__init__(embed_dim)
        name = fresh_name(name or "ncf_mlp")
        f = embed_dim // 4
        self.mlp_layers = self.create_mlp([8 * f, 4 * f, 2 * f, f],
                                          name=name)
        self.predict_layer = Linear(f, 1, initializer=init.xavier_normal(),
                                    name=f"{name}_predict")

    def __call__(self, embeddings, label):
        flat = array_reshape_op(embeddings,
                                output_shape=(-1, 2 * self.embed_dim))
        prediction = array_reshape_op(
            self.predict_layer(self.mlp_layers(flat)), output_shape=(-1,))
        return self.output(prediction, label)


class NeuMFHead(RatingModelHead):
    """Neural MF (reference neumf.py:6): with ``f = D // 5`` the first
    ``f`` dims of each embedding feed the GMF branch, the remaining
    ``4f`` feed the MLP tower ``[8f, 4f, 2f, f]``; concat -> ``2f -> 1``."""

    def __init__(self, embed_dim, name=None):
        if embed_dim % 5:
            raise ValueError("NeuMFHead needs embed_dim % 5 == 0 "
                             f"(got {embed_dim})")
        super().__init__(embed_dim)
        name = fresh_name(name or "neumf")
        f = embed_dim // 5
        self.factor_num = f
        self.mlp_layers = self.create_mlp([8 * f, 4 * f, 2 * f, f],
                                          name=name)
        self.predict_layer = Linear(2 * f, 1,
                                    initializer=init.xavier_normal(),
                                    name=f"{name}_predict")

    def __call__(self, embeddings, label):
        f = self.factor_num
        embeddings = array_reshape_op(
            embeddings, output_shape=(-1, 2, self.embed_dim))
        gmf_embs = slice_op(embeddings, begin_pos=(0, 0, 0),
                            output_shape=(-1, -1, f))
        mlp_embs = slice_op(embeddings, begin_pos=(0, 0, f),
                            output_shape=(-1, -1, -1))
        output_gmf = reduce_mul_op(gmf_embs, axes=(1,))
        input_mlp = array_reshape_op(
            mlp_embs, output_shape=(-1, 2 * (self.embed_dim - f)))
        output_mlp = self.mlp_layers(input_mlp)
        prediction = array_reshape_op(
            self.predict_layer(concat_op(output_gmf, output_mlp, axis=-1)),
            output_shape=(-1,))
        return self.output(prediction, label)


REC_HEADS = {"mf": MFHead, "gmf": GMFHead, "mlp": MLPHead,
             "neumf": NeuMFHead}


class NCFModel:
    """Head + shared (user|item) table, the reference driver's shape:
    ids ``[B, 2]`` where item ids are pre-offset by ``num_users``
    (examples/rec/run_compressed.py builds the same single table over
    users+items so compression methods see one id space)."""

    @scoped_init
    def __init__(self, num_users, num_items, embed_dim, head="neumf",
                 embedding=None, name="ncf"):
        # scoped_init (one name_scope per instance, the model-constructor
        # convention): head/layer names must not depend on process-global
        # fresh_name state or checkpoint keys drift with construction
        # order (ADVICE r3)
        self.embedding = embedding or Embedding(
            num_users + num_items, embed_dim, name=name)
        self.head = REC_HEADS[head](embed_dim)

    def __call__(self, ids, label):
        return self.head(self.embedding(ids), label)
