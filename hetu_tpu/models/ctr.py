"""CTR models: Wide&Deep, DeepFM, DCN, DLRM.

Reference: examples/ctr/models/{wdl_adult,wdl_criteo,dfm_criteo,dcn_criteo}.py
and tools/EmbeddingMemoryCompression/methods/../models (DLRM/WDL/DCN/DeepFM).
The embedding tables here are graph Variables (XLA gather path); swapping in
a PS-backed CacheSparseTable (ps/cstable.py) gives the HET bounded-staleness
path for tables that don't fit HBM.
"""

from __future__ import annotations

import numpy as np

from ..graph.node import VariableOp, Op, scoped_init
from .. import initializers as init
from ..layers import Linear, Embedding, Sequence, fresh_name
from ..ops import (array_reshape_op, concat_op, relu_op, sigmoid_op,
                   embedding_lookup_op, reduce_sum_op, reduce_mean_op,
                   binarycrossentropywithlogits_op, mul_op, matmul_op,
                   batch_matmul_op, transpose_op)


class SparseFeatureEmbedding:
    """One shared table over hashed/offset sparse slots: ids [B, F] -> [B, F*D].

    ``packed=True`` (or "auto") stores the table in the TPU-native
    PACKED layout — ``[num_rows/q, 128]`` with q = 128/dim logical rows
    per lane-line (ops/pallas/sparse_densify.py): the gradient needs no
    XLA scatter (194 us -> 44 us at W&D bench shapes) and the dense
    Adam update fuses into a single pass over the table (294 -> 164 us).
    Same math, different storage: use ``host_table``/``load_rows`` to
    exchange standard [num_rows, dim] weights."""

    def __init__(self, num_embeddings, dim, num_fields, name="sparse_emb",
                 packed=False):
        from ..ops.pallas.sparse_densify import pack_factor, packed_rows
        if packed == "auto":
            packed = pack_factor(dim) > 0
        if packed and not pack_factor(dim):
            raise ValueError(f"embedding dim {dim} does not pack into "
                             "128 lanes (needs dim | 128)")
        self.packed = bool(packed)
        self.num_embeddings = num_embeddings
        self.dim = dim
        self.num_fields = num_fields
        if self.packed:
            self.table = VariableOp(
                fresh_name(f"{name}_packed"),
                (packed_rows(num_embeddings, dim), 128),
                init.normal(0.0, 0.01))
        else:
            self.table = VariableOp(fresh_name(name),
                                    (num_embeddings, dim),
                                    init.normal(0.0, 0.01))

    def __call__(self, ids):
        if self.packed:
            from ..ops.embedding import packed_embedding_lookup_op
            return packed_embedding_lookup_op(self.table, ids, self.dim)
        e = embedding_lookup_op(self.table, ids)  # [B, F, D]
        return e

    def host_table(self, params):
        """Standard [num_rows, dim] numpy view of the table from an
        executor's params (unpacks the packed layout)."""
        w = np.asarray(params[self.table.name])
        if not self.packed:
            return w
        return w.reshape(-1, self.dim)[:self.num_embeddings]

    def load_rows(self, params, weights):
        """Install standard [num_rows, dim] weights into an executor's
        params (packs them when the table is packed)."""
        import jax.numpy as jnp
        weights = np.asarray(weights, np.float32)
        if not self.packed:
            params[self.table.name] = jnp.asarray(weights)
            return
        from ..ops.pallas.sparse_densify import pack_table
        params[self.table.name] = pack_table(weights)


def make_wdl_scorer(model):
    """Pure-jax WDL forward over PRE-GATHERED embedding rows.

    The serving path (serving/embedding/) gathers rows from the tiered
    host/device store instead of the in-graph table, so the dense half
    of the model must run WITHOUT the graph executor.  This pulls the
    wide/deep/out layer weights out of an executor's params by their
    canonical names (the adapters.py pattern for the decode tiers) and
    returns ``score(params, rows [B, F, D], dense [B, num_dense]) ->
    logits [B]`` — the same math as ``WDL.__call__`` with the embedding
    lookup replaced by the ``rows`` operand."""
    import jax.numpy as jnp

    wide_w, wide_b = model.wide.weight.name, model.wide.bias.name
    deep = [(l.weight.name, l.bias.name) for l in model.deep]
    out_w, out_b = model.out.weight.name, model.out.bias.name
    num_sparse, dim = model.num_sparse, model.embedding_dim
    names = ([wide_w, wide_b, out_w, out_b]
             + [n for pair in deep for n in pair])

    def score(params, rows, dense):
        flat = rows.reshape(rows.shape[0], num_sparse * dim)
        x = jnp.concatenate([flat, dense], axis=1)
        for wn, bn in deep:
            x = jnp.maximum(jnp.dot(x, params[wn]) + params[bn], 0.0)
        logit = (jnp.dot(x, params[out_w]) + params[out_b]
                 + jnp.dot(dense, params[wide_w]) + params[wide_b])
        return logit.reshape(-1)

    return score, tuple(names)


class WDL:
    """Wide & Deep (reference wdl_criteo: 13 dense + 26 sparse slots)."""

    @scoped_init
    def __init__(self, num_embeddings, embedding_dim=16, num_sparse=26,
                 num_dense=13, hidden=(256, 256, 256), name="wdl",
                 ps_embedding=None, packed_embedding=False):
        # ps_embedding: a ps.PSEmbedding — the HET cached-PS path for tables
        # that don't fit HBM (reference examples/ctr hybrid_wdl: embeddings
        # via PS + cache, dense params via the device optimizer)
        if ps_embedding is not None and packed_embedding:
            raise ValueError("packed_embedding applies to the in-graph "
                             "table; it cannot combine with ps_embedding "
                             "(the PS store owns the row layout)")
        self.emb = ps_embedding or SparseFeatureEmbedding(
            num_embeddings, embedding_dim, num_sparse, name=f"{name}_emb",
            packed=packed_embedding)
        # wide part: linear over dense features
        self.wide = Linear(num_dense, 1, name=f"{name}_wide")
        dims = [num_sparse * embedding_dim + num_dense] + list(hidden)
        self.deep = []
        for i in range(len(hidden)):
            self.deep.append(Linear(dims[i], dims[i + 1],
                                    name=f"{name}_deep{i}"))
        self.out = Linear(dims[-1], 1, name=f"{name}_out")
        self.num_sparse = num_sparse
        self.embedding_dim = embedding_dim

    def __call__(self, dense, sparse_ids):
        e = self.emb(sparse_ids)
        flat = array_reshape_op(
            e, output_shape=(-1, self.num_sparse * self.embedding_dim))
        x = concat_op(flat, dense, axis=1)
        for l in self.deep:
            x = relu_op(l(x))
        logit = self.out(x) + self.wide(dense)
        return array_reshape_op(logit, output_shape=(-1,))

    def loss(self, dense, sparse_ids, labels):
        logit = self(dense, sparse_ids)
        return reduce_mean_op(
            binarycrossentropywithlogits_op(logit, labels))


class FMSecondOrderOp(Op):
    """0.5 * ((sum_f e)^2 - sum_f e^2) summed over dim -> [B]."""

    def _compute(self, input_vals, ctx):
        import jax.numpy as jnp
        (e,) = input_vals  # [B, F, D]
        s = jnp.sum(e, axis=1)
        s2 = jnp.sum(e * e, axis=1)
        return 0.5 * jnp.sum(s * s - s2, axis=-1)


class DeepFM:
    """DeepFM (reference dfm_criteo)."""

    @scoped_init
    def __init__(self, num_embeddings, embedding_dim=16, num_sparse=26,
                 num_dense=13, hidden=(256, 256), name="dfm",
                 ps_embedding=None, packed_embedding=False):
        if ps_embedding is not None and packed_embedding:
            raise ValueError("packed_embedding applies to the in-graph "
                             "table; it cannot combine with ps_embedding "
                             "(the PS store owns the row layout)")
        self.emb = ps_embedding or SparseFeatureEmbedding(
            num_embeddings, embedding_dim, num_sparse, name=f"{name}_emb",
            packed=packed_embedding)
        self.first_order = VariableOp(f"{name}_fo", (num_embeddings, 1),
                                      init.normal(0.0, 0.01))
        dims = [num_sparse * embedding_dim + num_dense] + list(hidden)
        self.deep = [Linear(dims[i], dims[i + 1], name=f"{name}_deep{i}")
                     for i in range(len(hidden))]
        self.out = Linear(dims[-1], 1, name=f"{name}_out")
        self.num_sparse = num_sparse
        self.embedding_dim = embedding_dim

    def __call__(self, dense, sparse_ids):
        e = self.emb(sparse_ids)                      # [B, F, D]
        fo = embedding_lookup_op(self.first_order, sparse_ids)  # [B, F, 1]
        fo = reduce_sum_op(array_reshape_op(fo, output_shape=(-1, self.num_sparse)),
                           axes=1)                    # [B]
        so = FMSecondOrderOp(e)                       # [B]
        flat = array_reshape_op(
            e, output_shape=(-1, self.num_sparse * self.embedding_dim))
        x = concat_op(flat, dense, axis=1)
        for l in self.deep:
            x = relu_op(l(x))
        deep_out = array_reshape_op(self.out(x), output_shape=(-1,))
        return fo + so + deep_out

    def loss(self, dense, sparse_ids, labels):
        return reduce_mean_op(binarycrossentropywithlogits_op(
            self(dense, sparse_ids), labels))


class CrossLayerOp(Op):
    """DCN cross: x0 * (x·w) + b + x (reference dcn_criteo cross_layer)."""

    def _compute(self, input_vals, ctx):
        import jax.numpy as jnp
        x0, x, w, b = input_vals
        xw = jnp.einsum("bd,d->b", x, w)
        return x0 * xw[:, None] + b + x


class DCN:
    """Deep & Cross Network."""

    @scoped_init
    def __init__(self, num_embeddings, embedding_dim=16, num_sparse=26,
                 num_dense=13, num_cross=3, hidden=(256, 256), name="dcn",
                 ps_embedding=None, packed_embedding=False):
        if ps_embedding is not None and packed_embedding:
            raise ValueError("packed_embedding applies to the in-graph "
                             "table; it cannot combine with ps_embedding "
                             "(the PS store owns the row layout)")
        self.emb = ps_embedding or SparseFeatureEmbedding(
            num_embeddings, embedding_dim, num_sparse, name=f"{name}_emb",
            packed=packed_embedding)
        d = num_sparse * embedding_dim + num_dense
        self.cross_w = [VariableOp(f"{name}_cw{i}", (d,),
                                   init.normal(0.0, 0.01))
                        for i in range(num_cross)]
        self.cross_b = [VariableOp(f"{name}_cb{i}", (d,), init.zeros())
                        for i in range(num_cross)]
        dims = [d] + list(hidden)
        self.deep = [Linear(dims[i], dims[i + 1], name=f"{name}_deep{i}")
                     for i in range(len(hidden))]
        self.out = Linear(d + dims[-1], 1, name=f"{name}_out")
        self.num_sparse = num_sparse
        self.embedding_dim = embedding_dim

    def __call__(self, dense, sparse_ids):
        e = self.emb(sparse_ids)
        flat = array_reshape_op(
            e, output_shape=(-1, self.num_sparse * self.embedding_dim))
        x0 = concat_op(flat, dense, axis=1)
        x = x0
        for w, b in zip(self.cross_w, self.cross_b):
            x = CrossLayerOp(x0, x, w, b)
        h = x0
        for l in self.deep:
            h = relu_op(l(h))
        both = concat_op(x, h, axis=1)
        return array_reshape_op(self.out(both), output_shape=(-1,))

    def loss(self, dense, sparse_ids, labels):
        return reduce_mean_op(binarycrossentropywithlogits_op(
            self(dense, sparse_ids), labels))


class DLRMInteractionOp(Op):
    """Pairwise dot interactions (DLRM): [B,F,D] -> [B, F*(F-1)/2]."""

    def _compute(self, input_vals, ctx):
        import jax.numpy as jnp
        (e,) = input_vals
        z = jnp.einsum("bfd,bgd->bfg", e, e)
        f = e.shape[1]
        iu, ju = jnp.triu_indices(f, k=1)
        return z[:, iu, ju]


class DLRM:
    @scoped_init
    def __init__(self, num_embeddings, embedding_dim=16, num_sparse=26,
                 num_dense=13, bottom=(512, 256), top=(512, 256),
                 name="dlrm", ps_embedding=None, packed_embedding=False):
        if ps_embedding is not None and packed_embedding:
            raise ValueError("packed_embedding applies to the in-graph "
                             "table; it cannot combine with ps_embedding "
                             "(the PS store owns the row layout)")
        self.emb = ps_embedding or SparseFeatureEmbedding(
            num_embeddings, embedding_dim, num_sparse, name=f"{name}_emb",
            packed=packed_embedding)
        bd = [num_dense] + list(bottom) + [embedding_dim]
        self.bottom = [Linear(bd[i], bd[i + 1], name=f"{name}_bot{i}")
                       for i in range(len(bd) - 1)]
        f = num_sparse + 1
        td = [f * (f - 1) // 2 + embedding_dim] + list(top)
        self.top = [Linear(td[i], td[i + 1], name=f"{name}_top{i}")
                    for i in range(len(td) - 1)]
        self.out = Linear(td[-1], 1, name=f"{name}_out")
        self.num_sparse = num_sparse
        self.embedding_dim = embedding_dim

    def __call__(self, dense, sparse_ids):
        x = dense
        for l in self.bottom:
            x = relu_op(l(x))
        e = self.emb(sparse_ids)  # [B, F, D]
        xe = array_reshape_op(x, output_shape=(-1, 1, self.embedding_dim))
        all_e = concat_op(xe, e, axis=1)
        inter = DLRMInteractionOp(all_e)
        h = concat_op(inter, x, axis=1)
        for l in self.top:
            h = relu_op(l(h))
        return array_reshape_op(self.out(h), output_shape=(-1,))

    def loss(self, dense, sparse_ids, labels):
        return reduce_mean_op(binarycrossentropywithlogits_op(
            self(dense, sparse_ids), labels))
