"""ResNet for CIFAR (reference: examples/cnn/models/ResNet.py pattern —
ResNet-18/34 with BasicBlock; the v0 end-to-end gate model per SURVEY §7.3).
"""

from __future__ import annotations

from contextlib import nullcontext

import numpy as np

from ..graph.node import scoped_init, stage

from ..layers import (Conv2d, BatchNorm, Linear, Sequence, Identity)
from ..ops import (relu_op, global_avg_pool2d_op, array_reshape_op,
                   avg_pool2d_op)


class BasicBlock:
    expansion = 1

    def __init__(self, in_planes, planes, stride=1, name="block",
                 channels_last=False):
        cl = channels_last
        self.conv1 = Conv2d(in_planes, planes, 3, stride=stride, padding=1,
                            bias=False, channels_last=cl,
                            name=f"{name}_conv1")
        self.bn1 = BatchNorm(planes, channels_last=cl, name=f"{name}_bn1")
        self.conv2 = Conv2d(planes, planes, 3, stride=1, padding=1,
                            bias=False, channels_last=cl,
                            name=f"{name}_conv2")
        self.bn2 = BatchNorm(planes, channels_last=cl, name=f"{name}_bn2")
        self.shortcut = None
        if stride != 1 or in_planes != planes * self.expansion:
            self.sc_conv = Conv2d(in_planes, planes * self.expansion, 1,
                                  stride=stride, bias=False,
                                  channels_last=cl,
                                  name=f"{name}_scconv")
            self.sc_bn = BatchNorm(planes * self.expansion,
                                   channels_last=cl, name=f"{name}_scbn")
            self.shortcut = lambda x: self.sc_bn(self.sc_conv(x))

    def __call__(self, x):
        out = relu_op(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        sc = self.shortcut(x) if self.shortcut else x
        return relu_op(out + sc)


class ResNet:
    """``pipeline_stages=k`` stages construction for the graph pipeline
    executor (stem on stage 0, blocks split evenly, pool+fc on the last
    stage); batchnorm running stats thread through the pipeline's
    stateful-update path (graph_pipeline.py _fwd_micro)."""

    @scoped_init
    def __init__(self, num_blocks=(2, 2, 2, 2), num_classes=10,
                 name="resnet", pipeline_stages=None, channels_last=False):
        # channels_last: inputs are [B, H, W, C] and every activation
        # stays NHWC (zero layout transposes — fully TPU-native); the
        # default NCHW input contract matches the reference examples/cnn
        self.pipeline_stages = pipeline_stages
        self.channels_last = channels_last
        self.in_planes = 64
        self.conv1 = Conv2d(3, 64, 3, stride=1, padding=1, bias=False,
                            channels_last=channels_last,
                            name=f"{name}_conv1")
        self.bn1 = BatchNorm(64, channels_last=channels_last,
                             name=f"{name}_bn1")
        self.layers = []
        for i, (planes, n, stride) in enumerate(
                zip((64, 128, 256, 512), num_blocks, (1, 2, 2, 2))):
            blocks = []
            for j in range(n):
                blocks.append(BasicBlock(self.in_planes, planes,
                                         stride if j == 0 else 1,
                                         channels_last=channels_last,
                                         name=f"{name}_l{i}b{j}"))
                self.in_planes = planes * BasicBlock.expansion
            self.layers.append(blocks)
        self.fc = Linear(512, num_classes, name=f"{name}_fc")

    def _scope(self, flat_idx, n_flat):
        S = self.pipeline_stages
        if not S:
            return nullcontext()
        if flat_idx is None:
            return stage(0)
        bounds = np.array_split(np.arange(n_flat), S)
        for s, chunk in enumerate(bounds):
            if flat_idx in chunk:
                return stage(s)
        return stage(S - 1)

    def __call__(self, x):
        flat = [b for blocks in self.layers for b in blocks]
        with self._scope(None, len(flat)):
            out = relu_op(self.bn1(self.conv1(x)))
        for i, b in enumerate(flat):
            with self._scope(i, len(flat)):
                out = b(out)
        with (stage(self.pipeline_stages - 1) if self.pipeline_stages
              else nullcontext()):
            out = global_avg_pool2d_op(out,
                                       channels_last=self.channels_last)
            return self.fc(out)


def resnet18(num_classes=10, channels_last=False):
    return ResNet((2, 2, 2, 2), num_classes, channels_last=channels_last)


def resnet34(num_classes=10, channels_last=False):
    return ResNet((3, 4, 6, 3), num_classes, channels_last=channels_last)
