"""KV-cache autoregressive decoding for the GPT tier.

Same architecture as llama_decode.py (one jitted prefill + lax.scan
decode with a scan-carried, position-masked K/V cache, static shapes)
specialized to the GPT block: LayerNorm with bias, biased q/k/v/out
projections, gelu MLP, learned position embeddings, tied LM head.
Consumes Executor params by the GPTModel naming contract.

NOTE: the learned position table caps generation at
``config.seq_len`` total positions (rotary models have no such cap) —
build the model with seq_len >= prompt + max_new.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ._decode_common import layer_norm as _ln
from ._decode_common import (make_picker, make_attend, assemble,
                             executor_generate)


def make_layer_params(config, name):
    """Per-layer param lookup by the GPTModel naming contract; returns
    ``layer_params(params, i) -> dict`` (shared with serving)."""
    del config

    def layer_params(params, i):
        our = f"{name}_h{i}"
        return {k: params[f"{our}_{v}"] for k, v in {
            "ln1_g": "ln1_scale", "ln1_b": "ln1_bias",
            "ln2_g": "ln2_scale", "ln2_b": "ln2_bias",
            "wq": "attn_q_weight", "bq": "attn_q_bias",
            "wk": "attn_k_weight", "bk": "attn_k_bias",
            "wv": "attn_v_weight", "bv": "attn_v_bias",
            "wo": "attn_out_weight", "bo": "attn_out_bias",
            "w1": "ffn_in_weight", "b1": "ffn_in_bias",
            "w2": "ffn_out_weight", "b2": "ffn_out_bias",
        }.items()}

    return layer_params


def make_block(config, gather=None):
    """One GPT decoder layer over an explicit K/V cache; same signature
    family as llama_decode.make_block minus rotary (GPT positions are a
    learned table added at embedding time).  ``gather`` is the
    tensor-parallel replicate-back hook (see llama_decode.make_block);
    identity when not sharded."""
    c = config
    hd = c.hidden_size // c.num_heads
    attend = make_attend(hd)
    g = gather if gather is not None else (lambda x: x)

    def block(lp, x, ck, cv, pos_mask, write_at):
        b, sq, _ = x.shape
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q = (h @ lp["wq"] + lp["bq"]).reshape(b, sq, c.num_heads, hd)
        k = (h @ lp["wk"] + lp["bk"]).reshape(b, sq, c.num_heads, hd)
        v = (h @ lp["wv"] + lp["bv"]).reshape(b, sq, c.num_heads, hd)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, write_at, axis=2)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, write_at, axis=2)
        o = attend(q, ck, cv, pos_mask)
        o = g(o.transpose(0, 2, 1, 3).reshape(b, sq, c.hidden_size))
        x = g(x + o @ lp["wo"] + lp["bo"])
        f = _ln(x, lp["ln2_g"], lp["ln2_b"])
        f = jax.nn.gelu(f @ lp["w1"] + lp["b1"])   # approximate, as gelu_op
        return g(x + g(f) @ lp["w2"] + lp["b2"]), ck, cv

    return block


def make_chunk_embed(config, name):
    """Embedding + learned-position rows + mask for one prefill CHUNK
    per lane (llama_decode.make_chunk_embed's GPT sibling, minus
    rotary).  Returns ``chunk_inputs(params, tokens [B, C], starts [B],
    t) -> (x [B, C, H], mask [B, C, t])``.  Position rows are gathered
    with clipping against the wpe table (pad-tail rows past seq_len
    must not fault); the mask derives from the unclipped rows so those
    lanes stay exact where it matters — they are never emitted."""
    del config

    def chunk_inputs(params, tokens, starts, t):
        emb = params[f"{name}_wte_table"]
        wpe = params[f"{name}_wpe"]
        cl = tokens.shape[1]
        rows = starts[:, None] + jnp.arange(cl)[None, :]     # [B, C]
        rc = jnp.clip(rows, 0, wpe.shape[0] - 1)
        mask = jnp.arange(t)[None, None, :] <= rows[:, :, None]
        return emb[tokens] + wpe[rc], mask

    return chunk_inputs


def make_logits(config, name):
    del config

    def logits_of(params, h_last):
        h = _ln(h_last, params[f"{name}_ln_f_scale"],
                params[f"{name}_ln_f_bias"])
        return h @ params[f"{name}_wte_table"].T     # tied head

    return logits_of


def build_greedy_decode(config, max_new, name="gpt", temperature=0.0,
                        top_k=0):
    """Returns jitted ``fn(params, prompt_ids [B, P][, key]) ->
    [B, P+max_new]`` for a GPTModel (pre-norm, tied head)."""
    c = config
    hd = c.hidden_size // c.num_heads

    layer_params = make_layer_params(c, name)
    block = make_block(c)
    logits_of = make_logits(c, name)
    pick = make_picker(temperature, top_k)

    @jax.jit
    def decode(params, prompt_ids, key=None):
        if key is None:
            key = jax.random.key(0)
        b, p_len = prompt_ids.shape
        total = p_len + max_new
        assert total <= c.seq_len, (
            f"learned positions cover seq_len={c.seq_len} < "
            f"prompt+max_new={total}")
        emb = params[f"{name}_wte_table"]
        wpe = params[f"{name}_wpe"]
        lps = [layer_params(params, i) for i in range(c.num_layers)]
        kshape = (b, c.num_heads, total, hd)
        dtype = emb.dtype

        x = emb[prompt_ids] + wpe[None, :p_len]
        pre_mask = (jnp.arange(total)[None, :]
                    <= jnp.arange(p_len)[:, None])
        caches = []
        for lp in lps:
            ck = jnp.zeros(kshape, dtype)
            cv = jnp.zeros(kshape, dtype)
            x, ck, cv = block(lp, x, ck, cv, pre_mask, 0)
            caches.append((ck, cv))
        key, k0 = jax.random.split(key)
        first = pick(logits_of(params, x[:, -1:, :]),
                     k0).astype(prompt_ids.dtype)

        def step(carry, t):
            tok, caches, key = carry
            key, kt = jax.random.split(key)
            pos = p_len + t
            x = emb[tok] + jax.lax.dynamic_slice_in_dim(
                wpe, pos, 1, 0)[None]
            mask = (jnp.arange(total) <= pos)[None, :]
            new_caches = []
            for lp, (ck, cv) in zip(lps, caches):
                x, ck, cv = block(lp, x, ck, cv, mask, pos)
                new_caches.append((ck, cv))
            nxt = pick(logits_of(params, x), kt).astype(tok.dtype)
            return (nxt, new_caches, key), tok[:, 0]

        (last, _, _), toks = jax.lax.scan(
            step, (first, caches, key), jnp.arange(max_new - 1))
        return assemble(prompt_ids, first, last, toks, max_new)

    return decode


def greedy_generate(executor, model, prompt_ids, max_new, name="gpt",
                    temperature=0.0, top_k=0, seed=0):
    fn = build_greedy_decode(model.config, max_new, name=name,
                             temperature=temperature, top_k=top_k)
    return executor_generate(fn, executor,
                             [jnp.asarray(prompt_ids, jnp.int32)], seed)
