from .mlp import MLP, LeNet
from .resnet import ResNet, resnet18, resnet34
from .bert import BertConfig, BertModel, BertForPreTraining
from .gpt import GPTConfig, GPTModel, GPTLMHeadModel, GPT_CONFIGS
from .ctr import WDL, DeepFM, DCN, DLRM
