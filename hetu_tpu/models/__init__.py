from .mlp import MLP, LeNet
from .resnet import ResNet, resnet18, resnet34
from .bert import (BertConfig, BertModel, BertForPreTraining,
                   BertForSequenceClassification)
from .gpt import GPTConfig, GPTModel, GPTLMHeadModel, GPT_CONFIGS
from .ctr import WDL, DeepFM, DCN, DLRM
from .gnn import (DistGCN15D, GCNLayerOp, distgcn_15d_op, gcn_conv_op,
                  normalized_adjacency)
from .llama import (LlamaConfig, LlamaModel, LlamaForCausalLM,
                    BaichuanForCausalLM, LLAMA_CONFIGS)
from .llama_decode import build_greedy_decode, greedy_generate
from .hf_import import (load_hf_bert_weights, load_hf_gpt2_weights,
                        load_hf_llama_weights, export_hf_llama_weights,
                        load_hf_mixtral_weights)
from .zoo import (LogReg, CNN3, AlexNet, VGG, vgg16, vgg19,
                  RNNClassifier, LSTMClassifier)
from .rec import (RatingModelHead, MFHead, GMFHead, MLPHead, NeuMFHead,
                  NCFModel, REC_HEADS)
from .transformer import (TransformerConfig, Seq2SeqTransformer,
                          sinusoidal_positions)
from .transformer_decode import build_seq2seq_decode, seq2seq_generate
