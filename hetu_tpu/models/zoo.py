"""Classic model zoo: the reference's remaining examples/cnn models.

Reference: examples/cnn/models/{LogReg,CNN,AlexNet,VGG,RNN,LSTM}.py.
Conv stacks reuse the layer library; the recurrent models ride the
scan-based ops (ops/rnn.py) instead of the reference's 28-step unrolled
graphs.  All default to the reference's MNIST/CIFAR shapes.
"""

from __future__ import annotations

import numpy as np

from ..graph.node import VariableOp, scoped_init
from .. import initializers as init
from ..layers import Linear, Conv2d, BatchNorm, MaxPool2d, Relu, Sequence
from ..ops import relu_op, array_reshape_op, max_pool2d_op
from ..ops.base import SimpleOp
from ..ops.rnn import rnn_op, lstm_op


def _last_step(hs):
    # [N, T, H] -> [N, H] (the classifier reads the final hidden state)
    return SimpleOp(lambda h: h[:, -1, :], "last_step", hs)


class LogReg:
    """Logistic regression (reference LogReg.py)."""

    @scoped_init
    def __init__(self, in_dim=784, num_classes=10, name="logreg"):
        self.fc = Linear(in_dim, num_classes, name=f"{name}_fc")

    def __call__(self, x):
        return self.fc(x)


class CNN3:
    """The reference's plain 3-conv "CNN" (CNN.py), MNIST shapes."""

    @scoped_init
    def __init__(self, in_channels=1, num_classes=10, name="cnn"):
        self.c1 = Conv2d(in_channels, 32, 5, padding=2, name=f"{name}_c1")
        self.c2 = Conv2d(32, 64, 5, padding=2, name=f"{name}_c2")
        self.fc = Linear(7 * 7 * 64, num_classes, name=f"{name}_fc")

    def __call__(self, x):
        x = max_pool2d_op(relu_op(self.c1(x)), kernel_H=2, kernel_W=2,
                          stride=2)
        x = max_pool2d_op(relu_op(self.c2(x)), kernel_H=2, kernel_W=2,
                          stride=2)
        x = array_reshape_op(x, output_shape=(-1, 7 * 7 * 64))
        return self.fc(x)


class AlexNet:
    """AlexNet for 28x28 inputs (reference AlexNet.py's MNIST variant)."""

    @scoped_init
    def __init__(self, in_channels=1, num_classes=10, name="alexnet"):
        n = name
        self.features = []
        chans = [(in_channels, 32, True), (32, 64, True), (64, 128, False),
                 (128, 256, False), (256, 256, True)]
        for i, (ci, co, pool) in enumerate(chans):
            self.features.append((Conv2d(ci, co, 3, padding=1,
                                         name=f"{n}_conv{i}"),
                                  BatchNorm(co, name=f"{n}_bn{i}"), pool))
        self.fc1 = Linear(256 * 3 * 3, 1024, name=f"{n}_fc1")
        self.fc2 = Linear(1024, 512, name=f"{n}_fc2")
        self.fc3 = Linear(512, num_classes, name=f"{n}_fc3")

    def __call__(self, x):
        for conv, bn, pool in self.features:
            x = relu_op(bn(conv(x)))
            if pool:
                x = max_pool2d_op(x, kernel_H=2, kernel_W=2, stride=2)
        x = array_reshape_op(x, output_shape=(-1, 256 * 3 * 3))
        x = relu_op(self.fc1(x))
        x = relu_op(self.fc2(x))
        return self.fc3(x)


_VGG_PLANS = {
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


class VGG:
    """VGG-16/19 with BN (reference VGG.py), CIFAR 32x32 inputs."""

    @scoped_init
    def __init__(self, depth=16, in_channels=3, num_classes=10, name=None):
        name = name or f"vgg{depth}"
        plan = _VGG_PLANS[depth]
        chans = (64, 128, 256, 512, 512)
        self.blocks = []
        ci = in_channels
        for b, (n_layers, co) in enumerate(zip(plan, chans)):
            layers = []
            for l in range(n_layers):
                layers.append((Conv2d(ci, co, 3, padding=1,
                                      name=f"{name}_b{b}c{l}"),
                               BatchNorm(co, name=f"{name}_b{b}bn{l}")))
                ci = co
            self.blocks.append(layers)
        self.fc1 = Linear(512, 4096, name=f"{name}_fc1")
        self.fc2 = Linear(4096, 4096, name=f"{name}_fc2")
        self.fc3 = Linear(4096, num_classes, name=f"{name}_fc3")

    def __call__(self, x):
        for layers in self.blocks:
            for conv, bn in layers:
                x = relu_op(bn(conv(x)))
            x = max_pool2d_op(x, kernel_H=2, kernel_W=2, stride=2)
        x = array_reshape_op(x, output_shape=(-1, 512))
        x = relu_op(self.fc1(x))
        x = relu_op(self.fc2(x))
        return self.fc3(x)


def vgg16(num_classes=10):
    return VGG(16, num_classes=num_classes)


def vgg19(num_classes=10):
    return VGG(19, num_classes=num_classes)


class RNNClassifier:
    """Elman RNN over rows of a 28x28 image (reference RNN.py)."""

    @scoped_init
    def __init__(self, dim_in=28, dim_hidden=128, num_classes=10,
                 name="rnn"):
        std = init.normal(stddev=0.1)
        self.w_x = VariableOp(f"{name}_wx", (dim_in, dim_hidden), std)
        self.w_h = VariableOp(f"{name}_wh", (dim_hidden, dim_hidden), std)
        self.b = VariableOp(f"{name}_b", (dim_hidden,), init.zeros())
        self.head = Linear(dim_hidden, num_classes, name=f"{name}_out")
        self.dims = (dim_in, dim_hidden)

    def __call__(self, x):
        """x: [N, T, dim_in] (feed MNIST as [N, 28, 28])."""
        hs = rnn_op(x, self.w_x, self.w_h, self.b)
        return self.head(_last_step(hs))


class LSTMClassifier:
    """LSTM over rows of a 28x28 image (reference LSTM.py); torch-packed
    gates so torch.nn.LSTM weights transfer directly."""

    @scoped_init
    def __init__(self, dim_in=28, dim_hidden=128, num_classes=10,
                 name="lstm"):
        std = init.normal(stddev=0.1)
        self.w_ih = VariableOp(f"{name}_wih", (4 * dim_hidden, dim_in), std)
        self.w_hh = VariableOp(f"{name}_whh", (4 * dim_hidden, dim_hidden),
                               std)
        self.b_ih = VariableOp(f"{name}_bih", (4 * dim_hidden,),
                               init.zeros())
        self.b_hh = VariableOp(f"{name}_bhh", (4 * dim_hidden,),
                               init.zeros())
        self.head = Linear(dim_hidden, num_classes, name=f"{name}_out")
        self.dims = (dim_in, dim_hidden)

    def __call__(self, x):
        hs = lstm_op(x, self.w_ih, self.w_hh, self.b_ih, self.b_hh)
        return self.head(_last_step(hs))
