"""GPT-style decoder LM (reference: examples/auto_parallel/transformer
test_gpt2.py + Galvatron models/gpt — the 3D-parallel flagship).

Pre-norm causal transformer with tied LM head.  Parallelism comes from
strategy annotations (parallel/strategies.py MegatronLM / Galvatron configs)
or the shard_map fast path in parallel/tensor_parallel.py used by bench.
"""

from __future__ import annotations

from contextlib import nullcontext

import numpy as np

from ..graph.node import Op, VariableOp, stage, scoped_init
from .. import initializers as init
from ..layers import Embedding, LayerNorm, TransformerLayer
from ..ops import (array_reshape_op, matmul_op, reduce_mean_op,
                   softmax_cross_entropy_sparse_op, dropout_op)
from .bert import PositionIdsOp, MaskedMeanOp


class GPTConfig:
    def __init__(self, vocab_size=50257, hidden_size=768, num_layers=12,
                 num_heads=12, seq_len=1024, intermediate_size=None,
                 dropout_prob=0.1):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.seq_len = seq_len
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.dropout_prob = dropout_prob


# published size presets (match Galvatron gpt configs: 1.5b/2.7b/6.7b)
GPT_CONFIGS = {
    "gpt-small": dict(hidden_size=768, num_layers=12, num_heads=12),
    "gpt-medium": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt-1.5b": dict(hidden_size=1600, num_layers=48, num_heads=32),
    "gpt-2.7b": dict(hidden_size=2560, num_layers=32, num_heads=32),
    "gpt-6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32),
}


class GPTModel:
    """``pipeline_stages=k`` wraps construction in `ht.stage` scopes —
    embedding on stage 0, the layer stack split evenly, final LN (and the
    LM head built on top) on the last stage — so the model trains under
    the graph pipeline executor (parallel/graph_pipeline.py; reference
    raw_ctx staging, context.py:1430)."""

    @scoped_init
    def __init__(self, config, name="gpt", pipeline_stages=None):
        c = config
        self.config = c
        self.pipeline_stages = pipeline_stages
        self.wte = Embedding(c.vocab_size, c.hidden_size,
                             initializer=init.normal(0.0, 0.02),
                             name=f"{name}_wte")
        self.wpe = VariableOp(f"{name}_wpe", (c.seq_len, c.hidden_size),
                              init.normal(0.0, 0.01))
        self.layers = [
            TransformerLayer(c.hidden_size, c.num_heads,
                             c.intermediate_size, seq_len=c.seq_len,
                             dropout_rate=c.dropout_prob,
                             attn_dropout_rate=c.dropout_prob,
                             causal=True, pre_norm=True,
                             name=f"{name}_h{i}")
            for i in range(c.num_layers)]
        self.ln_f = LayerNorm(c.hidden_size, name=f"{name}_ln_f")

    def _scope(self, layer_idx=None):
        S = self.pipeline_stages
        if not S:
            return nullcontext()
        if layer_idx is None:
            return stage(0)
        # balanced split of the layer stack over stages
        bounds = np.array_split(np.arange(len(self.layers)), S)
        for s, chunk in enumerate(bounds):
            if layer_idx in chunk:
                return stage(s)
        return stage(S - 1)

    def __call__(self, input_ids):
        c = self.config
        with self._scope():
            x = self.wte(input_ids)
            x = x + PositionIdsOp(self.wpe, x, c.seq_len)
            if c.dropout_prob > 0:
                x = dropout_op(x, keep_prob=1.0 - c.dropout_prob)
        for i, layer in enumerate(self.layers):
            with self._scope(i):
                x = layer(x, seq_len=c.seq_len)
        with (stage(self.pipeline_stages - 1) if self.pipeline_stages
              else nullcontext()):
            return self.ln_f(x)


class GPTLMHeadModel:
    @scoped_init
    def __init__(self, config, name="gpt", pipeline_stages=None):
        self.transformer = GPTModel(config, name=name,
                                    pipeline_stages=pipeline_stages)
        self.config = config

    def __call__(self, input_ids):
        h = self.transformer(input_ids)
        h = array_reshape_op(h,
                             output_shape=(-1, self.config.hidden_size))
        return matmul_op(h, self.transformer.wte.weight, trans_B=True)

    def loss(self, input_ids, labels):
        """labels: [B, S] next-token ids with -1 at padded positions."""
        logits = self(input_ids)
        ce = softmax_cross_entropy_sparse_op(
            logits, array_reshape_op(labels, output_shape=(-1,)),
            ignored_index=-1)
        return MaskedMeanOp(ce, array_reshape_op(labels,
                                                 output_shape=(-1,)))
