"""Evaluation metrics (reference: python/hetu/metrics.py — accuracy, AUC,
F1, precision/recall, RMSE/MAE/NDCG for rec models).

Implemented on numpy host-side (metrics run on gathered predictions, not in
the jitted step; rank aggregation is the logger's job)."""

from __future__ import annotations

import numpy as np


def accuracy(y_pred, y_true):
    """y_pred: [N, C] logits/probs or [N] class ids; y_true: [N] ids."""
    y_pred = np.asarray(y_pred)
    y_true = np.asarray(y_true).reshape(-1)
    if y_pred.ndim > 1:
        y_pred = np.argmax(y_pred, axis=-1)
    return float(np.mean(y_pred.reshape(-1) == y_true))


def binary_accuracy(scores, y_true, threshold=0.5):
    scores = np.asarray(scores).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1)
    return float(np.mean((scores >= threshold) == (y_true > 0.5)))


def auc(scores, y_true):
    """ROC-AUC via the rank statistic (ties get midranks) — the standard
    CTR metric (reference metrics.py auc)."""
    scores = np.asarray(scores, np.float64).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1) > 0.5
    n_pos = int(y_true.sum())
    n_neg = y_true.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    sorted_scores = scores[order]
    # midranks for ties
    i = 0
    while i < len(sorted_scores):
        j = i
        while (j + 1 < len(sorted_scores)
               and sorted_scores[j + 1] == sorted_scores[i]):
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    sum_pos = ranks[y_true].sum()
    return float((sum_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def precision_recall_f1(y_pred, y_true, positive=1):
    y_pred = np.asarray(y_pred).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1)
    tp = np.sum((y_pred == positive) & (y_true == positive))
    fp = np.sum((y_pred == positive) & (y_true != positive))
    fn = np.sum((y_pred != positive) & (y_true == positive))
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    f1 = (2 * precision * recall / max(precision + recall, 1e-12)
          if (precision + recall) > 0 else 0.0)
    return float(precision), float(recall), float(f1)


def f1_score(y_pred, y_true, positive=1):
    return precision_recall_f1(y_pred, y_true, positive)[2]


def rmse(y_pred, y_true):
    y_pred = np.asarray(y_pred, np.float64).reshape(-1)
    y_true = np.asarray(y_true, np.float64).reshape(-1)
    return float(np.sqrt(np.mean((y_pred - y_true) ** 2)))


def mae(y_pred, y_true):
    y_pred = np.asarray(y_pred, np.float64).reshape(-1)
    y_true = np.asarray(y_true, np.float64).reshape(-1)
    return float(np.mean(np.abs(y_pred - y_true)))


def ndcg_at_k(scores, y_true, k=10):
    """NDCG@k for one query (rec-model metric)."""
    scores = np.asarray(scores).reshape(-1)
    y_true = np.asarray(y_true, np.float64).reshape(-1)
    order = np.argsort(-scores)[:k]
    gains = (2.0 ** y_true[order] - 1) / np.log2(np.arange(2, len(order) + 2))
    ideal_order = np.argsort(-y_true)[:k]
    ideal = ((2.0 ** y_true[ideal_order] - 1)
             / np.log2(np.arange(2, len(ideal_order) + 2)))
    denom = ideal.sum()
    return float(gains.sum() / denom) if denom > 0 else 0.0


def confusion_matrix(y_pred, y_true, num_classes):
    y_pred = np.asarray(y_pred).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1)
    m = np.zeros((num_classes, num_classes), np.int64)
    np.add.at(m, (y_true, y_pred), 1)
    return m


# -- serving latency statistics ---------------------------------------------
# Shared by the serving engine and `bench.py --serve` so the percentile
# math lives in exactly one place (linear interpolation over the sorted
# sample, numpy's default — stable for the small per-round request
# counts the bench replays).

def percentile(values, q):
    """q-th percentile (0..100) of a 1-D sample; nan on empty input."""
    values = np.asarray(list(values), np.float64).reshape(-1)
    if values.size == 0:
        return float("nan")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile q must be in [0, 100], got {q}")
    return float(np.percentile(values, q))


def latency_stats(values, percentiles=(50, 95, 99)):
    """Summary of one latency series: ``{"p50": .., "p95": .., "p99": ..,
    "mean": .., "max": .., "count": n}`` (seconds in, seconds out).
    None entries are dropped (a request that never reached the edge)."""
    values = [v for v in values if v is not None]
    out = {f"p{int(q)}": percentile(values, q) for q in percentiles}
    if values:
        arr = np.asarray(values, np.float64)
        out["mean"] = float(arr.mean())
        out["max"] = float(arr.max())
    else:
        out["mean"] = float("nan")
        out["max"] = float("nan")
    out["count"] = len(values)
    return out


def request_latency_summary(records, keys=("ttft", "tpot", "queue_wait"),
                            percentiles=(50, 95, 99)):
    """Per-key :func:`latency_stats` over serving request records (the
    dicts ``InferenceEngine.records`` accumulates)."""
    return {k: latency_stats((r.get(k) for r in records),
                             percentiles=percentiles) for k in keys}
