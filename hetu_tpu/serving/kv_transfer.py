"""Live KV page migration: the serving plane's page transfer wire.

Hetu's stance is that distribution is data flow, not plumbing — and a
decode stream's KV state is data like any other.  This module gives the
fleet a wire format for moving that state between sibling engines:

* ``snapshot_request`` serializes a mid-decode request — its refcounted
  pages as RAW pool rows (float32, or the quantized pool's codes +
  scales, never requantized) plus the host-side stream state (prompt,
  delivered tokens, position, effective sampling operands) — into a
  CRC32-framed blob.
* ``resume_request`` splices the blob into a sibling's pool
  (``PagedKVCache.import_pages`` + ``InferenceEngine.adopt_request``)
  and the stream continues BITWISE where it left off: paged sampling
  keys fold only the per-request seed and the consumed count, so the
  continuation is indistinguishable from an uninterrupted run.
* ``snapshot_prefix_cache`` / ``install_prefix_cache`` do the same for
  a replica's interned prefix pages, so the fleet-wide prefix cache
  survives the replica that built it (failover handoff).

Every parse error — torn frame, CRC mismatch, geometry drift, a
receiver pool out of pages — raises :class:`TransferError` and leaves
BOTH pools untouched (imported pages are rolled back before the raise).
The fleet catches it and falls back to teacher-forced replay, the
PR 12 bitwise oracle, so migration can only ever improve on replay:
same stream either way, fewer recomputed tokens when the wire works.

Framing: ``MAGIC`` then frames of ``[u32 length][payload][u32 crc32]``
(big-endian).  Frame 0 is a JSON header carrying stream state, pool
geometry, and array descriptors; subsequent frames are the raw array
bytes in header order.
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

MAGIC = b"HTKV1"
#: bump on any framing/header change; receivers refuse other versions
WIRE_VERSION = 1


class TransferError(RuntimeError):
    """A KV transfer failed (torn/corrupt frame, geometry mismatch,
    receiver refusal).  Both pools are left untouched; the caller falls
    back to teacher-forced replay."""


# -- framing ----------------------------------------------------------------
def _frame(payload):
    return (struct.pack(">I", len(payload)) + payload
            + struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF))


def _read_frame(blob, off):
    if off + 4 > len(blob):
        raise TransferError(
            f"torn frame at offset {off}: length header truncated")
    (n,) = struct.unpack_from(">I", blob, off)
    off += 4
    if off + n + 4 > len(blob):
        raise TransferError(
            f"torn frame at offset {off}: {n} payload bytes promised, "
            f"{len(blob) - off} remain")
    payload = blob[off:off + n]
    off += n
    (crc,) = struct.unpack_from(">I", blob, off)
    off += 4
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise TransferError(
            f"CRC32 mismatch in frame ending at offset {off} — "
            "transfer corrupt, falling back to replay")
    return payload, off


def _pack(header, arrays):
    parts = [MAGIC, _frame(json.dumps(
        header, separators=(",", ":")).encode())]
    for arr in arrays:
        parts.append(_frame(np.ascontiguousarray(arr).tobytes()))
    return b"".join(parts)


def _unpack(blob):
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        raise TransferError(
            f"transfer blob must be bytes, got {type(blob).__name__}")
    blob = bytes(blob)
    if not blob.startswith(MAGIC):
        raise TransferError("bad magic: not a KV transfer blob")
    hb, off = _read_frame(blob, len(MAGIC))
    try:
        header = json.loads(hb.decode())
    except Exception as e:
        raise TransferError(f"header frame is not JSON: {e}") from e
    if header.get("version") != WIRE_VERSION:
        raise TransferError(
            f"wire version {header.get('version')} != {WIRE_VERSION}")
    raws = []
    while off < len(blob):
        raw, off = _read_frame(blob, off)
        raws.append(raw)
    return header, raws


def _np_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp   # ml_dtypes names (fp8, bf16)
        return np.dtype(getattr(jnp, name))


def _describe(payload):
    """Deterministic (name, array) order for a pool payload."""
    names = (("k", "v") if payload["kv_dtype"] is None
             else ("k_codes", "k_scales", "v_codes", "v_scales"))
    return [(n, payload[n]) for n in names]


def _rebuild(descs, raws):
    if len(descs) != len(raws):
        raise TransferError(
            f"{len(descs)} arrays promised, {len(raws)} frames present")
    out = {}
    for d, raw in zip(descs, raws):
        dt = _np_dtype(d["dtype"])
        shape = tuple(int(x) for x in d["shape"])
        want = int(np.prod(shape)) * dt.itemsize
        if len(raw) != want:
            raise TransferError(
                f"array {d['name']!r}: {len(raw)} bytes for shape "
                f"{shape} dtype {d['dtype']} (want {want})")
        out[d["name"]] = np.frombuffer(raw, dt).reshape(shape)
    return out


def _check_geometry(header, cache):
    want = cache.page_geometry()
    got = header.get("geometry")
    if got != want:
        raise TransferError(
            f"pool geometry mismatch: donor {got} vs receiver {want} — "
            "pages cannot splice bit-identically, use replay")


# -- request transfer -------------------------------------------------------
def can_migrate(engine, req):
    """True when ``req``'s decode state can move off ``engine`` whole:
    paged engine without a ModelDraft, request running (not queued, not
    mid-chunked-prefill, not replaying a previous attempt — the replay
    remainder was already delivered and must not be re-emitted), with
    at least one generated token and decoding still to do."""
    return (getattr(engine, "_paged", False)
            and engine._draft is None
            and req.slot is not None
            and not req.finished
            and req.slot not in engine._prefilling
            and not req.replaying
            and 1 <= len(req.tokens) < req.max_new)


def snapshot_request(engine, req):
    """Serialize ``req``'s live decode state on ``engine`` into a
    transfer blob.  Pure read: the donor keeps decoding this request
    until the receiver acks (``engine.release_migrated``) — the caller
    must hold the replica lock across snapshot → resume → ack so the
    donor cannot advance past the snapshot in between."""
    if not can_migrate(engine, req):
        raise TransferError(
            f"request {req.rid} is not migratable on {engine.instance} "
            "(queued/prefilling/replaying/finished) — use replay")
    cache = engine.cache
    slot = req.slot
    position = int(cache.positions[slot])
    if position != int(req.prompt.size) + len(req.tokens) - 1:
        raise TransferError(
            f"request {req.rid}: position {position} torn vs prompt "
            f"{int(req.prompt.size)} + {len(req.tokens)} tokens")
    used = -(-position // cache.page_len)
    pages = cache.slot_pages(slot)[:used]
    payload = cache.export_pages(pages)
    arrays = _describe(payload)
    header = {
        "version": WIRE_VERSION, "kind": "request",
        "rid": req.rid,
        "prompt": [int(t) for t in req.prompt],
        "tokens": [int(t) for t in req.tokens],
        "max_new": int(req.max_new),
        "eos_id": None if req.eos_id is None else int(req.eos_id),
        "deadline": req.deadline,
        "position": position,
        "pages": int(used),
        # EFFECTIVE sampling operands (not the overrides): the receiver
        # replays them verbatim, so its engine defaults never leak into
        # a migrated stream's sampling key
        "temperature": float(engine._temps[slot]),
        "top_k": int(engine._topks[slot]),
        "seed": int(engine._seeds[slot]),
        "geometry": cache.page_geometry(),
        "arrays": [{"name": n, "shape": list(a.shape),
                    "dtype": a.dtype.name} for n, a in arrays],
    }
    return _pack(header, [a for _, a in arrays])


def blob_info(blob):
    """The parsed header of a transfer blob (full CRC walk — cheap at
    page-pool sizes).  For metrics/inspection; raises TransferError on
    a torn blob like any consumer would."""
    header, _ = _unpack(blob)
    return header


def resume_request(engine, blob, stream=None, verify=None):
    """Splice a :func:`snapshot_request` blob into ``engine`` and adopt
    the stream.  ``verify(header, arrays)`` is the receiver-side hook:
    called after CRC + geometry checks with the parsed header and the
    name->array dict; raising, or returning False, refuses the
    transfer.  Returns the adopted Request; raises
    :class:`TransferError` on ANY failure with the receiver pool rolled
    back (imported pages released) so replay can take over."""
    header, raws = _unpack(blob)
    if header.get("kind") != "request":
        raise TransferError(
            f"expected a request blob, got kind={header.get('kind')!r}")
    _check_geometry(header, engine.cache)
    arrays = _rebuild(header["arrays"], raws)
    if verify is not None:
        try:
            ok = verify(header, arrays)
        except Exception as e:
            raise TransferError(
                f"receiver verify hook rejected transfer: {e}") from e
        if ok is False:
            raise TransferError("receiver verify hook returned False")
    payload = dict(arrays)
    payload["kv_dtype"] = header["geometry"]["kv_dtype"]
    pages = engine.cache.import_pages(payload)
    if pages is None:
        raise TransferError(
            f"receiver {engine.instance} pool refused "
            f"{header['pages']} pages (out of free pages)")
    try:
        req = engine.adopt_request(
            np.asarray(header["prompt"], np.int32),
            header["tokens"], pages, header["position"],
            header["max_new"], rid=header.get("rid"), stream=stream,
            eos_id=header.get("eos_id"), deadline=header.get("deadline"),
            temperature=header["temperature"], top_k=header["top_k"],
            seed=header["seed"])
    except Exception as e:
        engine.cache.release_pages(pages)
        raise TransferError(
            f"receiver {engine.instance} failed to adopt "
            f"{header.get('rid')}: {e}") from e
    if req is None:
        engine.cache.release_pages(pages)
        raise TransferError(
            f"receiver {engine.instance} refused admission "
            "(no free slot)")
    return req


# -- prefix-cache transfer --------------------------------------------------
def snapshot_prefix_cache(engine, max_entries=None):
    """Serialize ``engine``'s interned prefix entries (hottest last)
    into a transfer blob, or None when there is nothing to hand off."""
    pc = getattr(engine, "prefix_cache", None)
    if pc is None:
        return None
    entries = pc.export_entries(max_entries=max_entries)
    if not entries:
        return None
    header_entries = []
    arrays = []
    for ent in entries:
        named = _describe(ent["payload"])
        header_entries.append({
            "tokens": [int(t) for t in ent["tokens"]],
            "n_tokens": int(ent["n_tokens"]),
            "arrays": [{"name": n, "shape": list(a.shape),
                        "dtype": a.dtype.name} for n, a in named]})
        arrays.extend(a for _, a in named)
    header = {"version": WIRE_VERSION, "kind": "prefix",
              "geometry": engine.cache.page_geometry(),
              "entries": header_entries}
    return _pack(header, arrays)


def install_prefix_cache(engine, blob):
    """Adopt a :func:`snapshot_prefix_cache` blob into ``engine``'s own
    prefix cache (dedup-aware; pool-full entries are skipped, not
    errors).  Returns the number of entries newly interned."""
    pc = getattr(engine, "prefix_cache", None)
    if pc is None:
        return 0
    header, raws = _unpack(blob)
    if header.get("kind") != "prefix":
        raise TransferError(
            f"expected a prefix blob, got kind={header.get('kind')!r}")
    _check_geometry(header, engine.cache)
    adopted = 0
    off = 0
    for ent in header["entries"]:
        n = len(ent["arrays"])
        arrays = _rebuild(ent["arrays"], raws[off:off + n])
        off += n
        payload = dict(arrays)
        payload["kv_dtype"] = header["geometry"]["kv_dtype"]
        pages = engine.cache.import_pages(payload)
        if pages is None:
            continue   # receiver pool full: a cache is best-effort
        if pc.adopt(np.asarray(ent["tokens"], np.int32),
                    ent["n_tokens"], pages):
            adopted += 1
    return adopted
