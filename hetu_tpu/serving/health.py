"""Replica health: the per-engine state machine + circuit breaker.

A fleet replica is not "up or down" — production engines degrade before
they die (watchdog trips piling up, iterations slowing, heartbeats going
stale) and the router must stop feeding a replica BEFORE it takes new
streams down with it.  Two small, clock-injectable pieces:

* :class:`ReplicaHealth` — the state machine

      HEALTHY -> DEGRADED -> QUARANTINED -> RESTARTING -> HEALTHY
                     |                          ^
                     +-- (clean ticks) ---------+--- DRAINING -> STOPPED

  driven by per-iteration observations (watchdog-trip deltas from the
  engine's registry-mirrored counters) and heartbeats (the driver bumps
  one per loop pass, so a wedged ``step()`` shows up as a stale
  heartbeat while the thread is stuck inside the jitted call).
  DEGRADED replicas still serve (the router just prefers others);
  QUARANTINED replicas serve nothing and their in-flight requests are
  failed over; DRAINING replicas finish what they hold but admit
  nothing new (rolling restarts); STOPPED is a drained replica waiting
  for restart or teardown.

* :class:`CircuitBreaker` — gates READMISSION after quarantine with
  exponential backoff: each consecutive open doubles the wait (capped),
  and the breaker only resets once the replica has proven itself with
  clean ticks after restart — a crash-looping replica backs off
  geometrically instead of flapping through restart cycles.

Neither class touches the engine: the fleet observes engine counters and
feeds them in, so health logic is testable with a hand clock and no jax.
"""

from __future__ import annotations

import time

from .. import telemetry as _telemetry

__all__ = ["HEALTH_STATES", "HEALTH_STATE_CODES", "HEALTHY", "DEGRADED",
           "QUARANTINED", "RESTARTING", "DRAINING", "STOPPED",
           "CircuitBreaker", "ReplicaHealth"]

HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"
RESTARTING = "restarting"
DRAINING = "draining"
STOPPED = "stopped"

#: every state a replica can be in, in severity order
HEALTH_STATES = (HEALTHY, DEGRADED, QUARANTINED, RESTARTING, DRAINING,
                 STOPPED)

#: numeric encoding for the ``hetu_fleet_engine_health_state`` gauge
#: (Prometheus gauges are floats; dashboards map code -> name)
HEALTH_STATE_CODES = {s: i for i, s in enumerate(HEALTH_STATES)}

#: states the router may dispatch new requests to
DISPATCHABLE = (HEALTHY, DEGRADED)


class CircuitBreaker:
    """Exponential-backoff gate on replica readmission.

    ``open_()`` records a failure and closes the gate for
    ``base * 2^(failures-1)`` seconds (capped); ``allow()`` reports
    whether the gate has re-opened (the half-open trial: the supervisor
    restarts the replica and watches it); ``close()`` resets after the
    replica proves healthy.  ``retry_after()`` is the remaining backoff
    — what :class:`~.fleet.FleetUnavailable` aggregates into its hint.
    """

    def __init__(self, base=0.25, cap=30.0, clock=None):
        if base <= 0 or cap < base:
            raise ValueError(
                f"need 0 < base <= cap, got base={base} cap={cap}")
        self.base = float(base)
        self.cap = float(cap)
        self._clock = clock if clock is not None else time.perf_counter
        self.failures = 0          # consecutive opens since last close
        self.opens = 0             # lifetime opens (telemetry)
        self._open_until = None

    @property
    def backoff(self):
        """The wait the NEXT open would impose (current: see
        ``retry_after``)."""
        return min(self.cap, self.base * 2 ** self.failures)

    def open_(self):
        """Record a failure; returns the backoff now in force."""
        wait = min(self.cap, self.base * 2 ** self.failures)
        self.failures += 1
        self.opens += 1
        self._open_until = self._clock() + wait
        return wait

    def allow(self, now=None):
        """True when the gate is closed or the backoff has elapsed."""
        if self._open_until is None:
            return True
        now = self._clock() if now is None else now
        return now >= self._open_until

    def retry_after(self, now=None):
        """Seconds until the gate re-opens (0.0 when it already has)."""
        if self._open_until is None:
            return 0.0
        now = self._clock() if now is None else now
        return max(0.0, self._open_until - now)

    def close(self):
        """The replica proved itself: reset the failure streak."""
        self.failures = 0
        self._open_until = None

    def __repr__(self):
        state = "closed" if self._open_until is None else \
            f"open({self.retry_after():.3f}s left)"
        return (f"CircuitBreaker({state}, failures={self.failures}, "
                f"opens={self.opens})")


class ReplicaHealth:
    """One replica's health state + the counters that drive it.

    ``observe(trips_delta)`` is called once per driver tick with the
    change in the engine's watchdog-trip count (slot quarantines AND
    raising steps both land there); ``heartbeat()`` once per driver loop
    pass.  Transitions the fleet imposes from outside (crash, wedge,
    drain, restart) go through :meth:`to`.
    """

    def __init__(self, name, degraded_after=1, quarantine_after=3,
                 recover_after=8, clock=None):
        if not 1 <= degraded_after <= quarantine_after:
            raise ValueError(
                f"need 1 <= degraded_after <= quarantine_after, got "
                f"{degraded_after} / {quarantine_after}")
        self.name = str(name)
        self.degraded_after = int(degraded_after)
        self.quarantine_after = int(quarantine_after)
        self.recover_after = int(recover_after)
        self._clock = clock if clock is not None else time.perf_counter
        self.state = HEALTHY
        self.consecutive_faults = 0
        self.clean_ticks = 0
        self.last_heartbeat = self._clock()
        self.last_reason = None     # why the last transition happened
        self.transitions = []       # [(state, reason)] history

    @property
    def dispatchable(self):
        return self.state in DISPATCHABLE

    def heartbeat(self):
        self.last_heartbeat = self._clock()

    def heartbeat_age(self, now=None):
        now = self._clock() if now is None else now
        return now - self.last_heartbeat

    def to(self, state, reason=None):
        """Externally-imposed transition (crash/wedge/drain/restart)."""
        if state not in HEALTH_STATES:
            raise ValueError(f"unknown health state {state!r}")
        if state != self.state:
            self.state = state
            self.last_reason = reason
            self.transitions.append((state, reason))
            # health transitions land in the flight-recorder ring so an
            # incident dump shows the replica's path into the fault
            fl = _telemetry.get_flight()
            if fl.enabled:
                fl.record({"e": "health", "engine": self.name,
                           "state": state, "reason": reason,
                           "t": time.perf_counter()})
        if state in (HEALTHY, RESTARTING):
            self.consecutive_faults = 0
            self.clean_ticks = 0
        return self.state

    def observe(self, trips_delta):
        """Feed one tick's fault evidence; returns the (possibly new)
        state.  Only HEALTHY<->DEGRADED->QUARANTINED moves happen here —
        draining/restarting replicas are under external control."""
        if self.state not in (HEALTHY, DEGRADED):
            return self.state
        if trips_delta > 0:
            self.consecutive_faults += int(trips_delta)
            self.clean_ticks = 0
            if self.consecutive_faults >= self.quarantine_after:
                return self.to(
                    QUARANTINED,
                    f"{self.consecutive_faults} consecutive watchdog "
                    "trips")
            if self.consecutive_faults >= self.degraded_after:
                return self.to(
                    DEGRADED,
                    f"{self.consecutive_faults} watchdog trip(s)")
            return self.state
        self.clean_ticks += 1
        if (self.state == DEGRADED
                and self.clean_ticks >= self.recover_after):
            self.consecutive_faults = 0
            return self.to(HEALTHY,
                           f"{self.clean_ticks} clean iterations")
        return self.state

    def snapshot(self):
        return {"state": self.state,
                "code": HEALTH_STATE_CODES[self.state],
                "consecutive_faults": self.consecutive_faults,
                "clean_ticks": self.clean_ticks,
                "last_reason": self.last_reason}

    def __repr__(self):
        return (f"ReplicaHealth({self.name}, {self.state}, "
                f"faults={self.consecutive_faults})")
