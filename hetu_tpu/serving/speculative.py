"""Speculative decoding over the paged serving engine.

A cheap DRAFT proposes ``k`` tokens per iteration; the target engine
verifies all of them in ONE fused teacher-forced step — the PR 6 replay
path widened to a ``[S, k+1]`` window — and commits the longest prefix
the target itself would have emitted:

* :func:`make_verify_fn` builds the fused verification program.  It
  feeds the window ``toks[:, j]`` at ``positions + j`` through the SAME
  slot-batched ``adapter.decode`` math as the one-token step program
  (gather pages once, carry the contiguous caches across the unrolled
  window, batch-scatter every written row back), and runs the SAME
  ``make_slot_picker`` lanes at consumed-count ``positions + j + 1`` —
  so ``picks[:, j]`` is bitwise the token the non-speculative twin
  would have emitted after consuming the first ``j + 1`` window tokens.
  Greedy acceptance is therefore bitwise prefix-match, and fixed-seed
  sampled acceptance is the same exact-match test (the picker's
  ``fold_in(fold_in(key, seed), consumed)`` lanes make the "leftover"
  sample after a rejection the target's own deterministic draw), which
  keeps replay-failover bit-exact mid-speculation.

* Rejected tokens need no device rollback.  The verify step writes all
  ``k + 1`` rows, but the engine only advances a slot's position over
  the accepted prefix: rows beyond it are exactly the stale rows the
  ``col <= position`` mask already never attends, and the next write at
  those positions overwrites them.  Admission reserves the ``k``-token
  lookahead worst-case (scheduler ``lookahead``), so the window can
  never scatter past a slot's reservation and admission stays the only
  refusal point.

Two draft flavors share the proposer surface:

* :class:`SelfDraft` — truncated-layer self-draft: the first
  ``draft_layers`` blocks of the TARGET model (same params, same page
  pool, layer-sliced gather) feed the full LM head.  Zero extra
  parameters, zero extra KV: the draft pass is carry-only and the
  verify step rewrites every row it touched.
* :class:`ModelDraft` — an injectable small model through the same
  adapter surface (``adapter_for``), with its own dense per-slot cache
  and a fused catchup + propose program: between verify iterations the
  draft teacher-forces the tokens the target committed, then rolls
  ``k`` proposals forward — one dispatch per engine iteration.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..models._decode_common import make_slot_picker
from .kv_cache import gather_pages, scatter_rows


def _p2(n):
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def make_verify_fn(adapter, pick, window):
    """The fused verify program: feed ``window`` candidate tokens per
    slot through the paged decode math in one dispatch.

    Signature (all static shapes)::

        (params, k, v, toks [S, W], positions [S], tables [S, MP],
         active [S], temps, top_ks, seeds)
        -> (k, v, picks [S, W], ok [S, W])

    ``picks[:, j]`` is the token the target emits after consuming
    ``toks[:, :j+1]`` — computed with the identical per-step ops and
    sampling lanes as the one-token step program, at consumed count
    ``positions + j + 1``.  All ``W`` written rows land in the slot's
    reserved pages (inactive lanes scatter into the sentinel page 0);
    committing or discarding them is purely host-side position
    bookkeeping."""

    def verify(params, k, v, toks, positions, tables, active,
               temps, top_ks, seeds):
        page_len, mp = k.shape[3], tables.shape[1]
        kc = gather_pages(k, tables)
        vc = gather_pages(v, tables)
        picks, oks = [], []
        for j in range(window):
            pos_j = positions + j
            logits, kc, vc = adapter.decode(params, toks[:, j], pos_j,
                                            kc, vc)
            oks.append(jnp.all(jnp.isfinite(logits), axis=-1))
            picks.append(pick(logits, temps, top_ks, seeds,
                              pos_j + 1).astype(jnp.int32))
        rows = positions[:, None] + jnp.arange(window)[None, :]  # [S, W]
        pidx = jnp.clip(rows // page_len, 0, mp - 1)
        pages = jnp.where(active[:, None],
                          jnp.take_along_axis(tables, pidx, axis=1), 0)
        offs = rows % page_len
        rix = jnp.clip(rows, 0, kc.shape[3] - 1)[:, None, None, :, None]
        krows = jnp.take_along_axis(kc, rix, axis=3)  # [S, L, KV, W, D]
        vrows = jnp.take_along_axis(vc, rix, axis=3)
        s, l, kv, w, d = krows.shape
        krows = jnp.transpose(krows, (0, 3, 1, 2, 4)).reshape(
            s * w, l, kv, d)
        vrows = jnp.transpose(vrows, (0, 3, 1, 2, 4)).reshape(
            s * w, l, kv, d)
        k = scatter_rows(k, pages.reshape(-1), offs.reshape(-1), krows)
        v = scatter_rows(v, pages.reshape(-1), offs.reshape(-1), vrows)
        picks = jnp.where(active[:, None], jnp.stack(picks, 1), 0)
        return k, v, picks, jnp.stack(oks, 1)

    return verify


def make_self_draft_fn(adapter, pick, k_draft, n_layers):
    """The truncated-layer self-draft program: roll ``k_draft``
    proposals forward through the first ``n_layers`` blocks of the
    target (layer-sliced page gather, carry-only — no pool writes; the
    verify step rewrites every row for all layers).  The picker runs
    the same ``(seed, consumed)`` lanes as the target, so a draft deep
    enough to agree with the target proposes exactly what verify will
    pick — acceptance degrades gracefully with depth, never
    correctness."""

    def draft(params, k, v, toks0, positions, tables,
              temps, top_ks, seeds):
        kc = gather_pages(k[:, :n_layers], tables)
        vc = gather_pages(v[:, :n_layers], tables)
        t = toks0
        props = []
        for j in range(k_draft):
            logits, kc, vc = adapter.decode(params, t, positions + j,
                                            kc, vc, n_layers=n_layers)
            t = pick(logits, temps, top_ks, seeds,
                     positions + j + 1).astype(jnp.int32)
            props.append(t)
        return jnp.stack(props, 1)

    return draft


class SelfDraft:
    """Truncated-layer self-draft config: propose with the target's
    first ``layers`` blocks (default ``max(1, L // 2)``, resolved by
    the engine).  ``layers == L`` is the degenerate full-depth draft —
    proposals match the target's picks and acceptance is ~total, which
    is what the acceptance-friendly bench trace uses to isolate the
    dispatch-amortization win at zero extra HBM."""

    kind = "self"

    def __init__(self, layers=None):
        self.layers = None if layers is None else int(layers)


class ModelDraft:
    """Injectable small-model draft over the same adapter surface.

    Owns a dense per-slot cache ``[S, L_d, KV_d, max_len, D_d]`` for
    the draft model and three host-visible phases, all driven by the
    engine:

    * :meth:`admit` — deposit the prompt's draft KV (one padded-bucket
      prefill per admission, traced once).
    * :meth:`propose` — ONE fused catchup + propose dispatch per engine
      iteration: each lane teacher-forces the ``cnt`` stream tokens the
      target committed since last sync (per-lane phase arithmetic with
      idempotent idle re-feeds keeps the shapes static), then rolls
      ``k`` proposals forward with the shared sampling lanes.
    * :meth:`release` — forget a retired slot (its rows go stale, the
      next admission's prefill overwrites them).

    The draft's speculative rows are overwritten by the next catchup at
    the same positions before they can ever be attended — the same
    stale-row invariant the target pool relies on."""

    kind = "model"

    #: shared compiled programs: (adapter type, name, geometry) ->
    #: {"prefill": fn, "step": fn, "traces": {...}} — ModelDraft
    #: instances over the same draft model reuse one executable set
    #: (fleet replicas each attach their own instance).
    _PROGRAMS = {}

    def __init__(self, executor, model, name="draft"):
        self.executor = executor
        self.model = model
        self.name = str(name)
        self._attached = False

    # -- lifecycle ---------------------------------------------------------
    def attach(self, engine):
        """Size caches + build programs from the target engine's
        geometry.  One ModelDraft serves one engine (per-slot state);
        pass a factory (zero-arg callable) as the engine's ``draft=``
        when replicas each need their own."""
        if self._attached:
            raise RuntimeError(
                "ModelDraft already attached to an engine; use a "
                "factory (draft=lambda: ModelDraft(...)) for fleets")
        from .adapters import adapter_for
        self._attached = True
        self.adapter = adapter_for(self.model, self.name)
        self.spec_k = int(engine._spec_k)
        self.n_slots = int(engine.cache.n_slots)
        self.max_len = int(engine.max_len)
        self.p_bucket = _p2(engine.max_prompt_len)
        cap = self.adapter.position_cap
        if cap is not None and self.max_len > cap:
            raise ValueError(
                f"draft model position cap {cap} < engine "
                f"max_len={self.max_len}")
        self.params = self.executor.params
        if engine.device is not None:
            self.params = jax.device_put(self.params, engine.device)
        a = self.adapter
        shape = (self.n_slots, a.layers, a.kv_heads, self.max_len,
                 a.head_dim)
        dtype = self.params[a.embed_param].dtype
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.pos = np.zeros(self.n_slots, np.int32)
        self._last = np.zeros(self.n_slots, np.int32)
        from .. import telemetry
        self._hbm = telemetry.get_hbm_ledger().alloc(
            "kv_cache", int(self.k.nbytes) + int(self.v.nbytes),
            owner=f"draft:{self.name}:{id(self):x}")
        key = (type(a).__name__, a.name, a.layers, a.kv_heads,
               a.head_dim, self.n_slots, self.max_len, self.p_bucket,
               self.spec_k, jax.default_backend())
        progs = ModelDraft._PROGRAMS.get(key)
        if progs is None:
            progs = self._build_programs()
            ModelDraft._PROGRAMS[key] = progs
        self._prefill = progs["prefill"]
        self._dstep = progs["step"]
        self._dcatch = progs["catch"]
        self._catch_w = progs["catch_w"]
        self.trace_counts = progs["traces"]

    def _build_programs(self):
        adapter, kk = self.adapter, self.spec_k
        window = kk + 1                       # max catchup per sync
        total = (window - 1) + kk
        catch_w = 4 * window                  # bulk-catchup bucket
        pick = make_slot_picker()
        traces = {"draft_prefill": 0, "draft_step": 0, "draft_catch": 0}

        def dprefill(params, k, v, prompt, slot):
            traces["draft_prefill"] += 1
            _, ks, vs = adapter.prefill(params, prompt)
            k = jax.lax.dynamic_update_slice(k, ks[None],
                                             (slot, 0, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(v, vs[None],
                                             (slot, 0, 0, 0, 0))
            return k, v

        def dstep(params, k, v, cat, cnt, base, temps, top_ks, seeds):
            # cat [S, W]: the cnt stream tokens committed since last
            # sync (cat[:, cnt-1] is the newest).  Lane phase at global
            # step j: i = j - (cnt - 1); i < 0 -> catchup feed
            # cat[:, j]; i == 0 -> feed the newest stream token;
            # i >= 1 -> feed the lane's own previous pick (proposal
            # i-1).  Filler steps past a lane's kk-th proposal
            # (i >= kk) pin at ONE PAST the last proposal row — always
            # a speculative row the next catchup overwrites before it
            # is ever attendable, never the newest real row (clamping
            # at kk-1 would re-feed a WRONG token onto the last
            # proposal row, and for kk == 1 onto the newest catchup
            # row itself).
            traces["draft_step"] += 1
            prev = cat[:, 0]
            picks = []
            for j in range(total):
                i = j - (cnt - 1)                             # [S]
                cat_tok = jnp.take_along_axis(
                    cat, jnp.minimum(j, cnt - 1)[:, None], axis=1)[:, 0]
                fed = jnp.where(i <= 0, cat_tok, prev)
                pos = (base + jnp.minimum(j, cnt - 1)
                       + jnp.clip(i, 0, kk))
                logits, k, v = adapter.decode(params, fed, pos, k, v)
                prev = pick(logits, temps, top_ks, seeds,
                            pos + 1).astype(jnp.int32)
                picks.append(prev)
            stacked = jnp.stack(picks, 1)                     # [S, total]
            idx = (cnt - 1)[:, None] + jnp.arange(kk)[None, :]
            props = jnp.take_along_axis(stacked, idx, axis=1)
            return k, v, props

        def dcatch(params, k, v, cat, cnt, base):
            # pure teacher-forced KV replay of up to catch_w committed
            # tokens per lane — the bulk half of a long catchup (the
            # engine ran gate-closed plain iterations and the backlog
            # outgrew one window).  Same phase arithmetic as dstep's
            # catchup prefix but no sampling lanes: catchup picks are
            # never consumed, so a 4x-wider no-pick program drains a
            # backlog in a fraction of the dispatches AND the
            # per-position op count.  Lanes with cnt < catch_w re-feed
            # their newest row idempotently.
            traces["draft_catch"] += 1
            for j in range(catch_w):
                jj = jnp.minimum(j, cnt - 1)                  # [S]
                tok = jnp.take_along_axis(cat, jj[:, None],
                                          axis=1)[:, 0]
                _, k, v = adapter.decode(params, tok, base + jj, k, v)
            return k, v

        donate = () if jax.default_backend() == "cpu" else (1, 2)
        return {"prefill": jax.jit(dprefill, donate_argnums=donate),
                "step": jax.jit(dstep, donate_argnums=donate),
                "catch": jax.jit(dcatch, donate_argnums=donate),
                "catch_w": catch_w,
                "traces": traces}

    # -- engine-driven phases ---------------------------------------------
    def admit(self, slot, prompt):
        """Deposit ``prompt``'s draft KV into ``slot`` (padded to the
        engine's prompt bucket; pad rows are overwritten by the first
        catchup before they become attendable)."""
        prompt = np.asarray(prompt, np.int32)
        buf = np.zeros((1, self.p_bucket), np.int32)
        buf[0, :prompt.size] = prompt
        self.k, self.v = self._prefill(self.params, self.k, self.v,
                                       jnp.asarray(buf), int(slot))
        self.pos[slot] = prompt.size
        self._last[slot] = prompt[-1]

    def release(self, slot):
        self.pos[slot] = 0
        self._last[slot] = 0

    def propose(self, work, temps, top_ks, seeds):
        """One fused catchup + propose dispatch.  ``work`` is
        ``[(slot, catchup_tokens), ...]`` where ``catchup_tokens`` are
        the stream tokens committed since the last sync, newest last
        (at least the newest token on a normal iteration).  Lags longer
        than one window (the engine ran plain-decode fallback
        iterations) are drained with extra idempotent rounds.  Returns
        proposals ``[n_slots, k]`` (rows of idle slots are garbage)."""
        W = self.spec_k + 1
        remaining = {int(s): list(map(int, t)) for s, t in work}
        # bulk-drain long backlogs (gate-closed fallback stretches)
        # through the wide no-pick catchup program first; the fused
        # round below then starts at most one window behind
        C = self._catch_w
        while max((len(t) for t in remaining.values()), default=0) > W:
            cat = np.zeros((self.n_slots, C), np.int32)
            cnt = np.ones(self.n_slots, np.int32)
            base = np.maximum(self.pos - 1, 0).astype(np.int32)
            cat[:, 0] = self._last
            for slot, toks in remaining.items():
                take = toks[:C]
                if not take:            # drained: idle re-feed
                    continue
                cat[slot, :len(take)] = take
                cnt[slot] = len(take)
                base[slot] = self.pos[slot]
                remaining[slot] = toks[C:]
                self.pos[slot] += len(take)
                self._last[slot] = take[-1]
            self.k, self.v = self._dcatch(self.params, self.k, self.v,
                                          jnp.asarray(cat),
                                          jnp.asarray(cnt),
                                          jnp.asarray(base))
        while True:
            cat = np.zeros((self.n_slots, W), np.int32)
            cnt = np.ones(self.n_slots, np.int32)
            base = np.maximum(self.pos - 1, 0).astype(np.int32)
            cat[:, 0] = self._last
            for slot, toks in remaining.items():
                take = toks[:W]
                if not take:            # drained: idle re-feed
                    continue
                cat[slot, :len(take)] = take
                cnt[slot] = len(take)
                base[slot] = self.pos[slot]
                remaining[slot] = toks[W:]
                self.pos[slot] += len(take)
                self._last[slot] = take[-1]
            self.k, self.v, props = self._dstep(
                self.params, self.k, self.v, jnp.asarray(cat),
                jnp.asarray(cnt), jnp.asarray(base),
                temps, top_ks, seeds)
            if not any(remaining.values()):
                return np.asarray(props)

    def close(self):
        if self._attached:
            self._hbm.free()
