"""Fleet-wide prefix caching over shared KV pages.

A shared prompt prefix is just shared PAGES: when a prompt finishes
prefill, its page-aligned prefixes are interned — the cache takes a
refcount on the slot's leading pages (``PagedKVCache.retain_pages``),
so they survive the writing slot's retirement.  A later prompt that
starts with an interned prefix has those pages mapped straight into
its new slot at admission (``alloc(shared=...)``): prefill starts
AFTER the shared span, the skipped rows are read through the gathered
block table, and TTFT drops by the skipped chunks.

Safety comes from three mechanisms layered on the refcounts:

* read-only by refcount — a page with refcount > 1 is never written;
  engine write-sets start past the shared span by construction, the
  host-side CoW guard (``HETU_COW_GUARD=1``, on in tests) asserts it
  at every dispatch, and ``ensure_writable`` forks a private copy
  (copy-on-write) if a divergent write ever does overlap.
* interning caps at ``prompt_len - 1`` tokens, so the final prompt row
  — the one whose logits seed the first generated token — is always
  computed by the admitted request itself, with its own sampling
  lanes.  Zero cross-request contamination: shared pages are a pure
  read-side dedup of identical (token, position) KV rows.
* eviction is LRU and *cooperative*: the pool's ``reclaim`` hook asks
  the cache to release entries only when an allocation is short of
  pages, so idle retained pages never refuse admission.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from .. import telemetry as _telemetry


class PrefixCache:
    """Page-granular prompt-prefix interning over one ``PagedKVCache``.

    One instance serves one pool (page ids are pool-local); a fleet
    enables one per replica and routes prefix-heavy requests to the
    replica reporting the longest hit (``EngineFleet`` tie-break).
    """

    def __init__(self, pool, max_entries=64):
        if not hasattr(pool, "retain_pages"):
            raise TypeError(
                "PrefixCache requires a PagedKVCache (shared prefixes "
                "are shared pages)")
        self.pool = pool
        self.max_entries = int(max_entries)
        if self.max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {self.max_entries}")
        # token-bytes of the prefix -> (pages tuple, n_tokens); insert
        # order is the LRU order (move_to_end on every hit)
        self._entries = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.interned = 0
        self.evicted = 0
        self._c_hits = _telemetry.get_registry().counter(
            "hetu_serving_prefix_hits_total",
            "Prefix-cache hits at admission (prompts whose leading "
            "pages were shared instead of re-prefilled)",
            labels=("pool",)).labels(pool=self.pool.label)
        # cooperative eviction: the pool calls back with its page
        # shortfall when an allocation comes up short
        pool.reclaim = self._reclaim

    # -- internals ---------------------------------------------------------
    def _max_pages(self, prompt):
        """Shareable page count: whole pages only, capped one token
        short of the prompt so the final row (the one that seeds the
        first generated token) is always computed by the request."""
        return (int(prompt.size) - 1) // self.pool.page_len

    def _evict_lru(self):
        key, (pages, _) = self._entries.popitem(last=False)
        self.evicted += 1
        return self.pool.release_pages(pages)

    def _reclaim(self, short):
        """Pool shortfall hook: evict LRU entries until ``short`` pages
        actually returned to the free list (an entry whose pages are
        still mapped by running slots frees nothing yet — its refcounts
        just drop to the holders').  Returns the pages freed; 0 tells
        the allocator to give up and refuse admission."""
        freed = 0
        while freed < int(short) and self._entries:
            freed += self._evict_lru()
        return freed

    # -- admission-side API ------------------------------------------------
    def lookup(self, prompt):
        """Longest interned page-prefix of ``prompt``: returns
        ``(pages, n_tokens)`` to map into the admitted slot, or None.
        The scheduler calls this at admission (``prefix_lookup``)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pl = self.pool.page_len
        for p in range(self._max_pages(prompt), 0, -1):
            ent = self._entries.get(prompt[:p * pl].tobytes())
            if ent is not None:
                self._entries.move_to_end(prompt[:p * pl].tobytes())
                self.hits += 1
                self._c_hits.inc()
                return list(ent[0]), int(ent[1])
        self.misses += 1
        return None

    def hit_tokens(self, prompt):
        """Length (tokens) of the longest interned prefix — the fleet's
        routing tie-break.  Pure peek: no counters, no LRU bump."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pl = self.pool.page_len
        for p in range(self._max_pages(prompt), 0, -1):
            if prompt[:p * pl].tobytes() in self._entries:
                return p * pl
        return 0

    def intern(self, prompt, slot):
        """Intern every page-aligned prefix of ``prompt`` from the
        pages ``slot`` holds after its prefill finished.  Idempotent
        per prefix (an already-interned one is just LRU-bumped); each
        new entry retains its pages so they outlive the slot."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        held = self.pool.slot_pages(slot)
        pl = self.pool.page_len
        n = min(len(held), self._max_pages(prompt))
        for p in range(1, n + 1):
            key = prompt[:p * pl].tobytes()
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            pages = tuple(int(x) for x in held[:p])
            self.pool.retain_pages(pages)
            self._entries[key] = (pages, p * pl)
            self.interned += 1
            while len(self._entries) > self.max_entries:
                self._evict_lru()

    # -- cross-replica handoff (serving/kv_transfer.py) ---------------------
    def export_entries(self, max_entries=None):
        """Host-side snapshot of interned entries (coldest first,
        hottest last — LRU order) for cross-replica handoff on
        failover: each item carries the prefix tokens, its token count,
        and the raw page payload from ``pool.export_pages``.  Pure
        read; the donor entries stay live."""
        items = list(self._entries.items())
        if max_entries is not None:
            items = items[-int(max_entries):]
        out = []
        for key, (pages, n_tokens) in items:
            out.append({"tokens": np.frombuffer(key, np.int32),
                        "n_tokens": int(n_tokens),
                        "payload": self.pool.export_pages(pages)})
        return out

    def adopt(self, tokens, n_tokens, pages):
        """Intern an entry around ALREADY-IMPORTED pages of this cache's
        own pool: the entry takes over the caller's one reference per
        page (mirroring what ``retain_pages`` would have granted).  On a
        dedup hit the existing entry wins and the caller's pages are
        released.  Returns True if a new entry landed."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n_tokens = int(n_tokens)
        pages = tuple(int(p) for p in pages)
        if (tokens.size != n_tokens
                or n_tokens != len(pages) * self.pool.page_len):
            raise ValueError(
                f"prefix entry shape torn: {tokens.size} tokens, "
                f"n_tokens={n_tokens}, {len(pages)} pages of "
                f"page_len={self.pool.page_len}")
        key = tokens.tobytes()
        if key in self._entries:
            self._entries.move_to_end(key)
            self.pool.release_pages(pages)
            return False
        self._entries[key] = (pages, n_tokens)
        self.interned += 1
        while len(self._entries) > self.max_entries:
            self._evict_lru()
        return True

    # -- reporting / lifecycle ---------------------------------------------
    def stats(self):
        total = self.hits + self.misses
        return {"entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (round(self.hits / total, 4) if total
                             else 0.0),
                "interned": self.interned,
                "evicted": self.evicted,
                "pages_retained": sum(len(pages) for pages, _
                                      in self._entries.values()),
                "cow_forks": self.pool.cow_fork_count}

    def close(self):
        """Release every retained page (so the pool's page audit
        balances after a drain) and unhook from the pool.  Idempotent."""
        while self._entries:
            self._evict_lru()
        if self.pool.reclaim is self._reclaim:
            self.pool.reclaim = None
