"""SLO-driven control plane: the fleet that operates itself.

PRs 4/9/10 gave the runtime eyes — queue depth, TPOT EWMAs, per-rid
timelines, per-program cost capture, incident counts — but nothing
ACTED on those signals: a bursty trace still rode FIFO-then-expire into
deadline misses while idle capacity sat undispatched.
:class:`FleetController` closes the loop.  It supervises one
:class:`~.fleet.EngineFleet` against a declared :class:`SLO` with three
actuators, all built on existing machinery:

* **autoscaling** — spawn (:meth:`~.fleet.EngineFleet.add_replica`) and
  drain (:meth:`~.fleet.EngineFleet.remove_replica`, the PR 6 drain
  path) replicas, driven by queue-depth and deadline-miss-rate EWMAs
  with hysteresis (separate up/down thresholds) and a cooldown so
  breaker flaps don't thrash scale.  Scale-down is two-phase and never
  blocks a tick: drain first, remove once drained — zero accepted-rid
  loss by construction.
* **predictive admission** — estimate each request's cost at
  ``submit()`` from measured signals (per-token decode cost ×
  ``max_new`` + bucketed prefill cost + queue wait at the best replica)
  and shed work that provably cannot meet its deadline at current load
  with a typed :class:`SLOReject` carrying the estimate, instead of
  admitting-then-expiring.  The estimator only rejects on EVIDENCE: with
  no measured decode cost yet, everything is admitted.
* **brownout degradation** — a staged degrade ladder
  (``normal → cap_max_new → shed_no_deadline → essential_only``)
  entered on sustained SLO violation once scale is exhausted and exited
  on sustained recovery.  ``essential_only`` rejects all external
  submits; failover/replay traffic re-homes through the fleet's
  internal ``_place`` path and is never throttled.  Every scale or
  degrade transition is recorded as a flight-recorder incident
  (``slo_scale`` / ``slo_degrade``) and a ``hetu_slo_*`` metric.

The controller is clock-injectable (defaults to the fleet's clock) and
drives the same way the fleet does: call :meth:`FleetController.tick`
after each ``pump()`` in manual mode, or :meth:`start` a supervisor
thread next to a threaded fleet.  ``telemetry.enable(debug=True)``
mounts :func:`slo_report` at ``/slo``.  The bench story is
``bench.py --slo``: a seeded bursty diurnal trace through a controlled
fleet vs its static twin, SLO attainment as the headline.
"""

from __future__ import annotations

import threading
import time
import warnings
import weakref

from .. import telemetry as _telemetry
from .health import (DEGRADED, DISPATCHABLE, DRAINING, HEALTHY,
                     QUARANTINED, STOPPED)
from .scheduler import TERMINAL_OK

#: the brownout ladder, mildest first; the level INDEXES this tuple
DEGRADE_LEVELS = ("normal", "cap_max_new", "shed_no_deadline",
                  "essential_only")

#: controllers alive in this process, for the /slo debug endpoint
_LIVE = weakref.WeakSet()


class SLO:
    """A declared serving objective the controller steers toward.

    ``deadline_miss_target`` is the tolerated fraction of finished
    requests retiring with ``finish_reason="deadline"`` (EWMA-smoothed).
    ``ttft_p99_s`` / ``tpot_p99_s`` bound the worst replica's latency
    EWMAs (None disables the bound).  ``max_shed_fraction`` caps the
    VOLUNTARY shed rate: once the controller is shedding more than this
    fraction of offered work it stops escalating the degrade ladder —
    shedding harder cannot be the fix for an SLO that counts shed work
    against attainment."""

    def __init__(self, deadline_miss_target=0.05, ttft_p99_s=None,
                 tpot_p99_s=None, max_shed_fraction=0.25):
        if not 0.0 <= deadline_miss_target <= 1.0:
            raise ValueError(
                f"deadline_miss_target must be in [0, 1], got "
                f"{deadline_miss_target}")
        if not 0.0 <= max_shed_fraction <= 1.0:
            raise ValueError(
                f"max_shed_fraction must be in [0, 1], got "
                f"{max_shed_fraction}")
        for label, v in (("ttft_p99_s", ttft_p99_s),
                         ("tpot_p99_s", tpot_p99_s)):
            if v is not None and v <= 0:
                raise ValueError(f"{label} must be > 0, got {v}")
        self.deadline_miss_target = float(deadline_miss_target)
        self.ttft_p99_s = None if ttft_p99_s is None else float(ttft_p99_s)
        self.tpot_p99_s = None if tpot_p99_s is None else float(tpot_p99_s)
        self.max_shed_fraction = float(max_shed_fraction)

    def as_dict(self):
        return {"deadline_miss_target": self.deadline_miss_target,
                "ttft_p99_s": self.ttft_p99_s,
                "tpot_p99_s": self.tpot_p99_s,
                "max_shed_fraction": self.max_shed_fraction}

    def __repr__(self):
        return f"SLO({self.as_dict()!r})"


class SLOReject(RuntimeError):
    """A submit refused by the controller BEFORE taking a slot.

    ``reason`` is one of ``"infeasible_deadline"`` (the predictive
    estimate proves the deadline cannot be met at current load),
    ``"no_deadline_brownout"`` (deadline-less traffic shed at degrade
    level >= 2), or ``"essential_only"`` (level 3 rejects all external
    work).  ``estimate`` carries the admission cost breakdown (seconds:
    ``wait_s``/``prefill_s``/``decode_s``/``total_s``/``slack_s``) when
    the rejection was estimate-driven, else None.  ``degrade_level``
    is the ladder level at rejection time."""

    def __init__(self, reason, estimate=None, degrade_level=0):
        self.reason = str(reason)
        self.estimate = estimate
        self.degrade_level = int(degrade_level)
        detail = ""
        if estimate is not None:
            detail = (f" (need {estimate['total_s']:.3f}s, have "
                      f"{estimate['slack_s']:.3f}s)")
        super().__init__(
            f"shed by SLO controller: {self.reason}"
            f"[level={DEGRADE_LEVELS[self.degrade_level]}]{detail}")


class CostModel:
    """Measured request-cost estimator for predictive admission.

    ``decode_s`` is an EWMA of seconds per generated token, fed from the
    fleet's per-replica TPOT EWMAs every tick (the best replica's —
    admission must only shed work that cannot meet its deadline even on
    the FASTEST path).  Prefill cost is bucketed by power-of-two prompt
    length (measured ``ttft - queue_wait`` per finished request, the
    PR 10 signal shape); an unseen bucket borrows the nearest measured
    one, and with no prefill evidence at all one decode step stands in.
    :meth:`prime` seeds ``decode_s`` from a
    :class:`~..telemetry.profiling.ProgramProfiler` observed profile so
    a controller can start warm from a prior ``--profile`` round.

    The governing principle: estimates only ever REJECT work when built
    on measurement — ``estimate()`` returns ``total_s=None`` (admit)
    until a decode cost exists."""

    def __init__(self, alpha=0.3):
        self.alpha = float(alpha)
        self.decode_s = None      # EWMA seconds / generated token
        self.prefill_s = {}       # pow2 bucket -> EWMA seconds
        # speculative decoding divisor: measured accepted tokens per
        # verify step (None until a speculating engine reports) — one
        # decode DISPATCH commits this many tokens, so per-token cost
        # derived from per-step timings must divide by it
        self.accepted_per_step = None

    @staticmethod
    def bucket(prompt_len):
        return max(1, int(prompt_len)).bit_length()

    def _fold(self, old, sample):
        s = float(sample)
        return s if old is None else \
            (1.0 - self.alpha) * old + self.alpha * s

    def observe_decode(self, seconds):
        if seconds is not None and seconds > 0:
            self.decode_s = self._fold(self.decode_s, seconds)

    def observe_speculation(self, accepted_per_step):
        """Fold a speculating engine's measured accepted-tokens-per-
        verify-step (the engine's own acceptance EWMA).  Clamped to
        >= 1: even a fully-rejecting window commits one token."""
        if accepted_per_step is not None and accepted_per_step > 0:
            self.accepted_per_step = self._fold(
                self.accepted_per_step, max(1.0, accepted_per_step))

    def observe_prefill(self, prompt_len, seconds):
        if seconds is None or seconds < 0:
            return
        b = self.bucket(prompt_len)
        self.prefill_s[b] = self._fold(self.prefill_s.get(b), seconds)

    def prefill_estimate(self, prompt_len):
        """Measured bucket, else the nearest measured bucket (larger
        preferred — conservative), else None."""
        if not self.prefill_s:
            return None
        b = self.bucket(prompt_len)
        if b in self.prefill_s:
            return self.prefill_s[b]
        near = min(self.prefill_s,
                   key=lambda k: (abs(k - b), -k))
        return self.prefill_s[near]

    def prime(self, profiler, decode="serve_decode"):
        """Seed ``decode_s`` from an OBSERVED program profile (one with
        measured ``steps_per_sec`` in its derived block).  Profiled
        steps are verify DISPATCHES: under speculative decoding each
        commits ``accepted_per_step`` tokens, so the per-token seed
        divides by the measured acceptance when one is known."""
        prof = profiler.profile(decode)
        derived = (prof or {}).get("derived") or {}
        sps = derived.get("steps_per_sec")
        if sps:
            per_step = 1.0 / float(sps)
            if self.accepted_per_step:
                per_step /= self.accepted_per_step
            self.observe_decode(per_step)
        return self.decode_s

    def as_dict(self):
        return {"decode_s": self.decode_s,
                "prefill_s": {f"2^{k}": v
                              for k, v in sorted(self.prefill_s.items())},
                "accepted_per_step": self.accepted_per_step,
                "alpha": self.alpha}


class FleetController:
    """Feedback controller steering one EngineFleet toward its SLO.

    Route external traffic through :meth:`submit` (predictive admission
    + the degrade ladder) and call :meth:`tick` once per pump/interval
    (sense → learn costs → scale → degrade).  ``min_engines`` /
    ``max_engines`` bound autoscaling; ``scale_up_queue`` /
    ``scale_down_queue`` are per-replica queue-depth thresholds with
    hysteresis (down << up); ``cooldown_s`` spaces scale actions so a
    breaker flap (quarantine → restart) cannot thrash scale;
    ``degrade_enter_ticks`` / ``degrade_exit_ticks`` are the sustained
    violation/recovery runs required to move the ladder.  All tunables
    are documented in docs/SLO.md."""

    def __init__(self, fleet, slo=None, *, clock=None, cost_model=None,
                 min_engines=1, max_engines=4,
                 scale_up_queue=4.0, scale_down_queue=0.5,
                 cooldown_s=2.0, ewma_alpha=0.3,
                 degrade_enter_ticks=10, degrade_exit_ticks=20,
                 brownout_max_new=16, admission_margin=1.0,
                 hbm_limit_bytes=None, hbm_safety=0.9,
                 mfu_scale_threshold=None, rebalance_ratio=None,
                 rebalance_cooldown_s=None, planner=None, alerts=None):
        if min_engines < 1:
            raise ValueError(
                f"min_engines must be >= 1, got {min_engines}")
        if max_engines < min_engines:
            raise ValueError(
                f"max_engines={max_engines} < min_engines={min_engines}")
        self.fleet = fleet
        self.slo = slo if slo is not None else SLO()
        self.name = fleet.name
        self._clock = clock if clock is not None else fleet._clock
        self.cost = cost_model if cost_model is not None else CostModel(
            alpha=ewma_alpha)
        self.min_engines = int(min_engines)
        self.max_engines = int(max_engines)
        self.scale_up_queue = float(scale_up_queue)
        self.scale_down_queue = float(scale_down_queue)
        self.cooldown_s = float(cooldown_s)
        self.ewma_alpha = float(ewma_alpha)
        self.degrade_enter_ticks = int(degrade_enter_ticks)
        self.degrade_exit_ticks = int(degrade_exit_ticks)
        self.brownout_max_new = int(brownout_max_new)
        self.admission_margin = float(admission_margin)
        # direction-5 memory/compute inputs: the HbmLedger's tracked
        # bytes vs device capacity gate scale-up (a replica whose KV
        # pool won't fit must not be added just to crash), and measured
        # MFU (ProgramProfiler.observe) reads as compute saturation
        self.hbm_limit_bytes = (None if hbm_limit_bytes is None
                                else int(hbm_limit_bytes))
        self.hbm_safety = float(hbm_safety)
        self.mfu_scale_threshold = (None if mfu_scale_threshold is None
                                    else float(mfu_scale_threshold))
        # opt-in decode-slot rebalancing: when one replica's observed
        # TPOT runs ratio× the fastest sibling's, live-migrate a stream
        # off it (None disables; the default controller never perturbs
        # placement behind the operator's back)
        if rebalance_ratio is not None and float(rebalance_ratio) <= 1.0:
            raise ValueError(
                f"rebalance_ratio must be > 1.0 (a hot/cold TPOT "
                f"ratio), got {rebalance_ratio}")
        self.rebalance_ratio = (None if rebalance_ratio is None
                                else float(rebalance_ratio))
        self.rebalance_cooldown_s = (
            float(cooldown_s) if rebalance_cooldown_s is None
            else float(rebalance_cooldown_s))
        self.hbm_headroom = None
        self.mfu = None
        self.hbm_blocked = 0
        # opt-in fleet replanning: a callable ``planner(ctl) -> fleet
        # plan dict | None`` invoked on HBM-blocked or SLO-violating
        # ticks (cooldown-spaced); whatever it returns is adopted via
        # :meth:`replan`.  ``hetu_tpu.planner.fleet_plan_from_controller``
        # is the intended implementation
        self._planner = planner
        self.replans = 0
        self._last_replan = None
        # opt-in trend input: an ``telemetry.alerts.AlertManager`` the
        # controller polls each tick (driving its TimeSeriesStore on
        # the controller's own cadence — no collector thread); firing
        # rules join _violations() as ``alert:<rule>`` entries, so
        # burn-rate pages apply scale/brownout pressure next to the
        # single-tick EWMAs
        self._alerts = alerts
        # controller state
        self.level = 0
        self.queue_ewma = None
        self.miss_ewma = None
        self.ticks = 0
        self.accepted = 0
        self.shed = 0
        self.capped = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.rebalances = 0
        self._last_rebalance = None
        self.degrade_entries = 0
        self.degrade_exits = 0
        self.max_level_seen = 0
        self._draining = set()
        self._last_scale = None
        self._last_fin = 0
        self._last_miss = 0
        self._viol_ticks = 0
        self._ok_ticks = 0
        self._viol_now = ()
        self._depth = 0
        self._rec_seen = {}       # (replica, incarnation) -> records idx
        self._thread = None
        self._running = False
        reg = _telemetry.get_registry()

        def _g(name, help):
            return reg.gauge(name, help,
                             labels=("controller",)).labels(
                                 controller=self.name)

        self._m_level = _g(
            "hetu_slo_degrade_level",
            "Brownout ladder level (0 normal, 1 cap_max_new, "
            "2 shed_no_deadline, 3 essential_only)")
        self._m_engines = _g(
            "hetu_slo_engines",
            "Live (non-draining) replicas under the controller")
        self._m_miss = _g(
            "hetu_slo_deadline_miss_ewma",
            "EWMA fraction of finished requests that missed their "
            "deadline")
        self._m_queue = _g(
            "hetu_slo_queue_depth_ewma",
            "EWMA of fleet-wide queued + running requests")
        self._m_shed_frac = _g(
            "hetu_slo_shed_fraction",
            "Fraction of offered requests shed by predictive admission "
            "or brownout")
        self._m_attain = _g(
            "hetu_slo_attainment",
            "Fraction of offered work (finished + shed) that completed "
            "healthily (eos/max_new)")
        self._m_headroom = _g(
            "hetu_slo_hbm_headroom",
            "Usable device HBM headroom in bytes (safety-scaled device "
            "capacity minus HbmLedger live bytes) seen by the "
            "controller's scale gate")
        self._m_scale = reg.counter(
            "hetu_slo_scale_events_total",
            "Autoscale actions taken by the controller",
            labels=("controller", "direction"))
        self._m_degrade = reg.counter(
            "hetu_slo_degrade_transitions_total",
            "Degrade-ladder transitions, by destination level",
            labels=("controller", "to"))
        self._m_rejects = reg.counter(
            "hetu_slo_admission_rejects_total",
            "Submits shed with SLOReject before taking a slot",
            labels=("controller", "reason"))
        self._m_replans = reg.counter(
            "hetu_plan_fleet_replans_total",
            "Planner-emitted fleet shapes adopted live via replan()",
            labels=("controller",))
        self._fl = _telemetry.get_flight()
        self._m_level.set(0)
        self._m_engines.set(len(fleet._replicas))
        _LIVE.add(self)

    # -- admission ---------------------------------------------------------
    def _reject(self, reason, estimate=None):
        self.shed += 1
        self._m_rejects.labels(controller=self.name, reason=reason).inc()
        self._m_shed_frac.set(self.shed_fraction())
        raise SLOReject(reason, estimate=estimate,
                        degrade_level=self.level)

    def estimate(self, prompt_len, max_new, now=None):
        """Admission-time cost estimate (seconds): best-replica queue
        wait + bucketed prefill + ``max_new`` decode steps.  Returns
        ``total_s=None`` when there is no measured decode cost yet —
        no evidence, no rejection."""
        now = self._clock() if now is None else now
        decode_s = self.cost.decode_s
        if decode_s is None:
            return {"wait_s": None, "prefill_s": None, "decode_s": None,
                    "total_s": None}
        wait = self._wait_estimate(decode_s)
        prefill = self.cost.prefill_estimate(prompt_len)
        if prefill is None:
            prefill = decode_s      # one step stands in
        total = wait + prefill + float(max_new) * decode_s
        return {"wait_s": wait, "prefill_s": prefill,
                "decode_s": decode_s, "total_s": total}

    def _wait_estimate(self, decode_s):
        """Expected queue wait on the BEST dispatchable replica: its
        outstanding token debt spread over its slots, at its observed
        decode rate."""
        best = None
        for rep in list(self.fleet._replicas):
            if not rep.health.dispatchable or rep.engine is None:
                continue
            b = rep.engine.scheduler.backlog()
            tpot = rep.tpot_ewma or decode_s
            slots = rep.engine.cache.n_slots
            debt = b["queued_tokens"] + b["running_tokens"]
            w = (debt / max(1, slots)) * tpot
            best = w if best is None else min(best, w)
        return 0.0 if best is None else best

    def submit(self, prompt, max_new, stream=None, eos_id=None,
               ttl=None, deadline=None, hedge=False):
        """Admit one external request through the degrade ladder and
        predictive admission, then route it via ``fleet.submit``.
        Raises :class:`SLOReject` (shed, no slot taken), or whatever
        ``fleet.submit`` raises once admitted."""
        now = self._clock()
        if ttl is not None:
            if deadline is not None:
                raise ValueError("pass ttl= or deadline=, not both")
            if ttl <= 0:
                raise ValueError(f"ttl must be > 0, got {ttl}")
            deadline = now + float(ttl)
        level = self.level
        if level >= 3:
            self._reject("essential_only")
        if level >= 2 and deadline is None:
            self._reject("no_deadline_brownout")
        eff_max_new = int(max_new)
        if level >= 1 and eff_max_new > self.brownout_max_new:
            eff_max_new = self.brownout_max_new
            self.capped += 1
        if deadline is not None:
            # prefix-cache-aware prefill: pages already interned on some
            # live replica are mapped at admission, not recomputed, so
            # the deadline estimate buckets only the uncached tail
            plen = _prompt_len(prompt)
            cached = 0
            for rep in self._live_replicas():
                pc = getattr(rep.engine, "prefix_cache", None)
                if pc is not None:
                    cached = max(cached, pc.hit_tokens(prompt))
            est = self.estimate(max(plen - cached, 1), eff_max_new,
                                now=now)
            if est["total_s"] is not None:
                slack = deadline - now
                est["slack_s"] = slack
                if est["total_s"] * self.admission_margin > slack:
                    self._reject("infeasible_deadline", estimate=est)
        freq = self.fleet.submit(prompt, eff_max_new, stream=stream,
                                 eos_id=eos_id, deadline=deadline,
                                 hedge=hedge)
        self.accepted += 1
        self._m_shed_frac.set(self.shed_fraction())
        return freq

    # -- sensing helpers ---------------------------------------------------
    def shed_fraction(self):
        offered = self.accepted + self.shed
        return self.shed / offered if offered else 0.0

    def _live_replicas(self):
        return [r for r in list(self.fleet._replicas)
                if r.health.state not in (DRAINING, STOPPED)]

    def _learn_costs(self):
        """Fold the fleet's measured signals into the cost model: the
        best replica TPOT becomes the decode cost, and every newly
        finished request's ``ttft - queue_wait`` becomes a prefill
        sample for its prompt-length bucket."""
        best = None
        for rep in list(self.fleet._replicas):
            if rep.tpot_ewma:
                best = rep.tpot_ewma if best is None \
                    else min(best, rep.tpot_ewma)
            eng = rep.engine
            if eng is None:
                continue
            # speculation-aware decode costs: TPOT EWMAs above already
            # reflect multi-token verify steps, but profiler-primed
            # per-step seeds need the measured divisor too
            aps = getattr(eng, "spec_accepted_per_step", None)
            if aps is not None:
                self.cost.observe_speculation(aps)
            key = (rep.name, rep.incarnation)
            seen = self._rec_seen.get(key, 0)
            recs = eng.records
            for rec in recs[seen:]:
                ttft = rec.get("ttft")
                qw = rec.get("queue_wait")
                pl = rec.get("prompt_len")
                if ttft is not None and qw is not None and pl:
                    self.cost.observe_prefill(
                        pl, max(0.0, ttft - qw))
            self._rec_seen[key] = len(recs)
        if best is not None:
            self.cost.observe_decode(best)

    def _device_hbm_limit(self):
        if self.hbm_limit_bytes is not None:
            return self.hbm_limit_bytes
        limit = 16 * 1024 ** 3   # v5e/v5p-class HBM default
        try:
            import jax
            stats = jax.devices()[0].memory_stats()
        except Exception:   # backend without memory_stats (CPU) — the
            stats = None    # nominal default above stands
        if stats and stats.get("bytes_limit"):
            limit = stats["bytes_limit"]
        return int(limit)

    def _sense_capacity(self):
        """Fold the telemetry plane's memory/compute evidence into the
        controller: HBM headroom (safety-scaled device capacity minus
        the ledger's live bytes) and the best measured MFU across
        captured program profiles (only ``observe``-d profiles carry
        one)."""
        led = _telemetry.get_hbm_ledger()
        headroom = (self.hbm_safety * self._device_hbm_limit()
                    - led.live_bytes())
        self.hbm_headroom = float(headroom)
        self._m_headroom.set(self.hbm_headroom)
        best = None
        for prof in _telemetry.get_profiler().profiles().values():
            mfu = (prof.get("derived") or {}).get("mfu")
            if mfu is not None:
                best = mfu if best is None else max(best, mfu)
        self.mfu = best

    def _kv_projection(self):
        """Projected kv_cache bytes ONE more replica would pin: the
        per-replica mean of the pool's live bytes (every replica of one
        fleet builds the same slot geometry)."""
        led = _telemetry.get_hbm_ledger()
        kv = led.live_bytes("kv_cache")
        n = sum(1 for r in self._live_replicas() if r.engine is not None)
        return kv / n if n else 0.0

    def _hbm_would_block(self):
        """True when one more replica's projected kv_cache pool exceeds
        the current headroom — scale-up is unavailable regardless of
        max_engines, and the degrade ladder must carry the pressure."""
        projected = self._kv_projection()
        return (self.hbm_headroom is not None and projected > 0
                and projected > self.hbm_headroom)

    def _violations(self):
        out = []
        if (self.miss_ewma or 0.0) > self.slo.deadline_miss_target:
            out.append("deadline_miss")
        live = self._live_replicas()
        if self._depth == 0:
            # the replica TTFT/TPOT EWMAs are finish-time signals: with
            # nothing in flight they go stale, and holding a brownout on
            # a stale reading would wedge the ladder open forever — an
            # idle fleet meets its latency bounds by definition
            return tuple(out)
        if self.slo.ttft_p99_s is not None:
            worst = max((r.ttft_ewma for r in live if r.ttft_ewma),
                        default=None)
            if worst is not None and worst > self.slo.ttft_p99_s:
                out.append("ttft")
        if self.slo.tpot_p99_s is not None:
            worst = max((r.tpot_ewma for r in live if r.tpot_ewma),
                        default=None)
            if worst is not None and worst > self.slo.tpot_p99_s:
                out.append("tpot")
        return tuple(out)

    # -- the control loop --------------------------------------------------
    def tick(self):
        """One sense → learn → actuate pass.  Call after each
        ``fleet.pump()`` in manual mode; the :meth:`start` thread calls
        it on an interval for threaded fleets."""
        now = self._clock()
        self.ticks += 1
        self._learn_costs()
        live = self._live_replicas()
        depth = 0
        for rep in live:
            if rep.engine is not None:
                sch = rep.engine.scheduler
                depth += len(sch.queue) + len(sch.running)
        a = self.ewma_alpha
        self.queue_ewma = float(depth) if self.queue_ewma is None else \
            (1.0 - a) * self.queue_ewma + a * depth
        # deadline-miss rate from the fleet's O(1) finish counters; a
        # tick with no finishes carries no signal UNLESS the fleet is
        # idle (an idle fleet meets its SLO by definition — this is the
        # recovery path out of a brownout once traffic stops)
        fin = sum(self.fleet.finish_counts.values())
        miss = self.fleet.finish_counts.get("deadline", 0)
        dfin, dmiss = fin - self._last_fin, miss - self._last_miss
        self._last_fin, self._last_miss = fin, miss
        sample = None
        if dfin > 0:
            sample = dmiss / dfin
        elif depth == 0:
            sample = 0.0
        if sample is not None:
            self.miss_ewma = sample if self.miss_ewma is None else \
                (1.0 - a) * self.miss_ewma + a * sample
        self._depth = depth
        self._sense_capacity()
        self._reap_draining()
        viol = self._violations()
        if self._alerts is not None:
            viol += tuple(f"alert:{r}" for r in self._alerts.poll(now))
        self._viol_now = viol
        self._maybe_replan(now, viol)
        self._autoscale(now, viol)
        self._degrade(now, viol)
        self._rebalance(now)
        # refresh the live gauges
        self._m_engines.set(len(self._live_replicas()))
        self._m_miss.set(self.miss_ewma or 0.0)
        self._m_queue.set(self.queue_ewma or 0.0)
        self._m_shed_frac.set(self.shed_fraction())
        self._m_attain.set(self.attainment())
        return self

    def _cool(self, now):
        return (self._last_scale is not None
                and now - self._last_scale < self.cooldown_s)

    def _autoscale(self, now, viol):
        live = self._live_replicas()
        n = len(live)
        pressure = (bool(viol)
                    or (self.queue_ewma or 0.0)
                    > self.scale_up_queue * max(1, n)
                    # compute-saturated: measured MFU above the
                    # threshold means the device, not the queue, is the
                    # bottleneck — more replicas is the only lever
                    or (self.mfu_scale_threshold is not None
                        and (self.mfu or 0.0) > self.mfu_scale_threshold))
        if pressure and n < self.max_engines and not self._cool(now):
            if self._hbm_would_block():
                # headroom-blocked: one more replica's kv_cache pool
                # would not fit the device — scaling up would trade an
                # SLO violation for an OOM.  Degrade handles pressure.
                self.hbm_blocked += 1
                # cooldown applies to the BLOCK too: sustained pressure
                # must not emit an incident per tick
                self._last_scale = now
                self._m_scale.labels(controller=self.name,
                                     direction="up_blocked_hbm").inc()
                self._fl.incident(
                    "slo_scale", health=self.fleet.health(),
                    extra={"controller": self.name,
                           "direction": "up_blocked_hbm",
                           "n_engines": n,
                           "projected_kv_bytes": int(
                               self._kv_projection()),
                           "hbm_headroom": int(self.hbm_headroom),
                           "violations": list(viol)})
                return
            name = self.fleet.add_replica()
            self._last_scale = now
            self.scale_ups += 1
            self._scale_event("up", name, now, viol)
            return
        calm = (not viol and self.level == 0
                and (self.queue_ewma or 0.0)
                < self.scale_down_queue * max(1, n)
                and (self.miss_ewma or 0.0)
                <= self.slo.deadline_miss_target / 2.0)
        if calm and n > self.min_engines and not self._cool(now):
            victim = self._scale_down_victim(live)
            if victim is None:
                return
            # migrate-then-drain: the victim's long decode tail moves
            # to surviving siblings NOW (live KV page migration), so
            # the two-phase removal isn't gated on its slowest stream;
            # anything non-migratable just drains out as before
            self.fleet.drain(victim.name, wait=False, migrate=True)
            self._draining.add(victim.name)
            self._last_scale = now
            self.scale_downs += 1
            self._scale_event("down", victim.name, now, viol)

    def _rebalance(self, now):
        """Decode-slot rebalancing (opt-in via ``rebalance_ratio=``):
        when the hottest replica's observed TPOT runs ``ratio``× the
        fastest sibling's — thermal throttle, noisy neighbor — one
        running stream is live-migrated off it per pass (bounded,
        cooldown-spaced) instead of waiting for the health machine to
        call the replica sick.  Queue pressure counts too: a replica
        that is both slow and loaded sheds first."""
        if self.rebalance_ratio is None:
            return
        if (self._last_rebalance is not None
                and now - self._last_rebalance
                < self.rebalance_cooldown_s):
            return
        cands = [r for r in self._live_replicas()
                 if r.health.state in DISPATCHABLE and r.tpot_ewma
                 and r.name not in self._draining]
        if len(cands) < 2:
            return
        # hottest by observed decode latency, load as the tie-break
        hot = max(cands, key=lambda r: (r.tpot_ewma, len(r.inflight)))
        cool = min(r.tpot_ewma for r in cands if r is not hot)
        if hot.tpot_ewma < self.rebalance_ratio * cool \
                or not hot.inflight:
            return
        moved = self.fleet.rebalance(hot.name, max_requests=1)
        if moved:
            self.rebalances += moved
            self._last_rebalance = now

    def _scale_down_victim(self, live):
        cands = [r for r in live
                 if r.health.state in DISPATCHABLE
                 and r.name not in self._draining]
        if len(cands) <= self.min_engines:
            return None
        return min(cands, key=lambda r: (len(r.inflight),
                                         -r.index))

    def _scale_event(self, direction, engine, now, viol):
        self._m_scale.labels(controller=self.name,
                             direction=direction).inc()
        self._fl.incident(
            "slo_scale", health=self.fleet.health(),
            extra={"controller": self.name, "direction": direction,
                   "engine": engine,
                   "n_engines": len(self._live_replicas()),
                   "queue_ewma": round(self.queue_ewma or 0.0, 4),
                   "miss_ewma": round(self.miss_ewma or 0.0, 4),
                   "violations": list(viol)})

    # -- fleet replanning --------------------------------------------------
    def _maybe_replan(self, now, viol):
        """Invoke the configured ``planner=`` callable on HBM-blocked
        or SLO-violating ticks (cooldown-spaced — the spacing applies
        to the ATTEMPT, so a planner with no feasible answer is not
        hammered every tick); any plan it returns is adopted through
        :meth:`replan`."""
        if self._planner is None:
            return
        if not (viol or self._hbm_would_block()):
            return
        if (self._last_replan is not None
                and now - self._last_replan < self.cooldown_s):
            return
        self._last_replan = now
        try:
            plan = self._planner(self)
        except Exception as e:   # planner failure must not kill tick
            warnings.warn(
                f"slo controller {self.name}: planner failed "
                f"{type(e).__name__}: {e}")
            return
        if plan:
            self.replan(plan)

    def replan(self, plan):
        """Adopt a planner-emitted fleet plan live — the actuator for
        ``hetu_tpu.planner.plan_fleet`` output (a ``hetu_fleet_plan``
        dict or just its ``shape`` block).

        Page-geometry changes update the fleet's shared engine kwargs
        and ROLLING-REPLACE every live replica: the freshly-geometried
        replicas are added FIRST, then the stale ones drain out with
        live KV page migration (the PR 17 machinery), so no accepted
        request is lost.  Pure count changes add replicas or drain the
        autoscaler's victims.  ``tp_size`` cannot change on a live
        fleet (tp sub-meshes are built at construction) — a mismatch is
        recorded in the report's notes, never silently applied.  The
        target replica count is clamped to ``[min_engines,
        max_engines]``.  Returns the adoption report."""
        shape = plan.get("shape", plan)
        live = [r for r in self._live_replicas()
                if r.name not in self._draining]
        target = int(shape.get("replicas", len(live)))
        clamped = max(self.min_engines, min(self.max_engines, target))
        notes = []
        if clamped != target:
            notes.append(f"replicas {target} clamped to {clamped} "
                         f"(min={self.min_engines}, "
                         f"max={self.max_engines})")
        fleet = self.fleet
        tp_now = int(getattr(fleet, "tp_size", 1))
        tp_want = int(shape.get("tp_size", tp_now))
        if tp_want != tp_now:
            notes.append(f"tp_size {tp_now} -> {tp_want} requires a "
                         f"fleet rebuild; keeping tp={tp_now}")
        geom = {}
        for key in ("page_len", "n_pages", "n_slots", "max_len"):
            want = shape.get(key)
            if want is None:
                continue
            cur = fleet._ekw.get(key)
            if cur is not None and int(cur) != int(want):
                geom[key] = int(want)
        added, removed = [], []
        if geom and not fleet._ekw.get("paged"):
            notes.append(f"geometry change {geom} ignored: engines are "
                         f"not paged")
            geom = {}
        if geom:
            fleet._ekw.update(geom)
            for _ in range(clamped):
                added.append(fleet.add_replica())
            for rep in live:
                fleet.drain(rep.name, wait=False, migrate=True)
                self._draining.add(rep.name)
                removed.append(rep.name)
        else:
            n = len(live)
            while n < clamped:
                added.append(fleet.add_replica())
                n += 1
            while n > clamped:
                victim = self._scale_down_victim(
                    [r for r in live if r.name not in removed])
                if victim is None:
                    notes.append(f"stopped at {n} replicas: no "
                                 f"drainable victim")
                    break
                fleet.drain(victim.name, wait=False, migrate=True)
                self._draining.add(victim.name)
                removed.append(victim.name)
                n -= 1
        # adopting a shape IS a scale action: cooldown keeps the
        # autoscaler from fighting the plan on the very next tick
        self._last_scale = self._clock()
        self.replans += 1
        self._m_replans.labels(controller=self.name).inc()
        report = {"adopted": True, "target_replicas": clamped,
                  "tp_size": tp_now, "added": added,
                  "draining": removed, "geometry": geom,
                  "notes": notes}
        self._fl.incident(
            "slo_replan", health=fleet.health(),
            extra={"controller": self.name, **report,
                   "n_engines": len(self._live_replicas())})
        return report

    def _reap_draining(self):
        """Finish two-phase scale-downs: remove replicas whose drain
        completed; re-drain any that a breaker restart revived."""
        for name in sorted(self._draining):
            rep = self.fleet._by_name(name)
            if rep is None:
                self._draining.discard(name)
                continue
            st = rep.health.state
            if st in (STOPPED, QUARANTINED):
                if self.fleet.remove_replica(name, wait=False):
                    self._draining.discard(name)
            elif st in (HEALTHY, DEGRADED):
                # auto_restart revived it mid-drain: drain again
                self.fleet.drain(name, wait=False)

    def _degrade(self, now, viol):
        # "can't scale" includes HBM-blocked below max_engines: the
        # ladder must carry the pressure when adding a replica would OOM
        at_max = (len(self._live_replicas()) >= self.max_engines
                  or self._hbm_would_block())
        if viol and at_max:
            self._viol_ticks += 1
            self._ok_ticks = 0
        elif not viol:
            self._ok_ticks += 1
            self._viol_ticks = 0
        else:
            # violating but scale-up is still available: let
            # autoscaling fix it before shedding anything
            self._viol_ticks = 0
        if (self._viol_ticks >= self.degrade_enter_ticks
                and self.level < len(DEGRADE_LEVELS) - 1
                and self.shed_fraction() <= self.slo.max_shed_fraction):
            self._set_level(self.level + 1, ",".join(viol))
            self._viol_ticks = 0
        elif self._ok_ticks >= self.degrade_exit_ticks and self.level > 0:
            self._set_level(self.level - 1, "recovered")
            self._ok_ticks = 0

    def _set_level(self, level, reason):
        old, self.level = self.level, int(level)
        if self.level > old:
            self.degrade_entries += 1
        else:
            self.degrade_exits += 1
        self.max_level_seen = max(self.max_level_seen, self.level)
        self._m_level.set(self.level)
        self._m_degrade.labels(controller=self.name,
                               to=DEGRADE_LEVELS[self.level]).inc()
        self._fl.incident(
            "slo_degrade", health=self.fleet.health(),
            extra={"controller": self.name,
                   "from": DEGRADE_LEVELS[old],
                   "to": DEGRADE_LEVELS[self.level],
                   "reason": reason,
                   "queue_ewma": round(self.queue_ewma or 0.0, 4),
                   "miss_ewma": round(self.miss_ewma or 0.0, 4),
                   "n_engines": len(self._live_replicas())})
        warnings.warn(
            f"slo controller {self.name}: degrade "
            f"{DEGRADE_LEVELS[old]} -> {DEGRADE_LEVELS[self.level]} "
            f"({reason})")

    # -- introspection -----------------------------------------------------
    def attainment(self):
        """Fraction of OFFERED work (finished + shed) that completed
        healthily (eos/max_new).  Shed and missed work both count
        against it — degrading is a controlled loss, not a free pass."""
        fc = self.fleet.finish_counts
        ok = sum(fc.get(r, 0) for r in TERMINAL_OK)
        offered = sum(fc.values()) + self.shed
        return ok / offered if offered else 1.0

    def report(self):
        """The /slo debug block: SLO, ladder position, EWMAs, cost
        model, and action counters."""
        return {
            "controller": self.name,
            "slo": self.slo.as_dict(),
            "level": self.level,
            "level_name": DEGRADE_LEVELS[self.level],
            "violations": list(self._viol_now),
            "alerts_firing": (None if self._alerts is None
                              else sorted(self._alerts.firing())),
            "n_engines": len(self._live_replicas()),
            "draining": sorted(self._draining),
            "ewma": {"queue_depth": self.queue_ewma,
                     "deadline_miss": self.miss_ewma},
            "cost_model": self.cost.as_dict(),
            "capacity": {
                "hbm_headroom": (None if self.hbm_headroom is None
                                 else int(self.hbm_headroom)),
                "projected_kv_bytes": int(self._kv_projection()),
                "mfu": self.mfu,
                "hbm_blocked": self.hbm_blocked},
            "shed_fraction": round(self.shed_fraction(), 4),
            "attainment": round(self.attainment(), 4),
            "counters": {"ticks": self.ticks,
                         "accepted": self.accepted,
                         "shed": self.shed,
                         "capped": self.capped,
                         "scale_ups": self.scale_ups,
                         "scale_downs": self.scale_downs,
                         "rebalances": self.rebalances,
                         "replans": self.replans,
                         "degrade_entries": self.degrade_entries,
                         "degrade_exits": self.degrade_exits,
                         "max_level_seen": self.max_level_seen},
        }

    # -- threaded drive ----------------------------------------------------
    def start(self, interval=0.05):
        """Run :meth:`tick` on a daemon supervisor thread (threaded
        fleets).  No-op when already running."""
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, args=(float(interval),), daemon=True,
            name=f"slo-{self.name}")
        self._thread.start()
        return self

    def _loop(self, interval):
        while self._running:
            try:
                self.tick()
            except Exception as e:    # the controller must never die
                warnings.warn(
                    f"slo controller {self.name}: tick error "
                    f"{type(e).__name__}: {e}")
            time.sleep(interval)

    def stop(self):
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def _prompt_len(prompt):
    try:
        return int(getattr(prompt, "size", None) or len(prompt))
    except TypeError:
        return 1


def slo_report():
    """{controller: report} for every live FleetController — the
    ``/slo`` debug endpoint payload."""
    return {c.name: c.report() for c in list(_LIVE)}
