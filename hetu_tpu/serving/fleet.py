"""Fleet serving: N supervised engine replicas behind a failover router.

PR 5 made one ``InferenceEngine`` survive poisoned slots, deadline
churn, and overload — but one engine is still one blast radius: a
crashed or wedged engine takes every in-flight stream with it.
``EngineFleet`` is the cluster-level robustness layer:

* **replicas** — N in-process :class:`~.engine.InferenceEngine`\\ s,
  one driver thread each (``threaded=True``; ``threaded=False`` gives a
  deterministic ``pump()`` loop for tests and seeded benches), pinned
  one-per-device when the backend has multiple devices;
* **latency-aware dispatch** — ``submit`` routes to the replica with
  the lowest ``(queue_depth + in_flight + 1) * TPOT_EWMA`` score, the
  telemetry signals PR 5 left as the "latency-aware admission"
  follow-up.  Request ids are CLUSTER-level: ``"e0-7"`` names the
  engine instance that admitted the request and stays with the request
  across failover;
* **health state machine** — each replica runs
  HEALTHY → DEGRADED → QUARANTINED → RESTARTING (health.py), driven by
  heartbeats (a wedged ``step()`` shows as a stale heartbeat) and
  watchdog-trip deltas; DRAINING/STOPPED support rolling restarts;
* **circuit breaker** — quarantine opens a per-replica breaker with
  exponential backoff; the supervisor restarts the replica only after
  the backoff elapses, and the breaker resets only after clean ticks —
  a crash-looping replica backs off geometrically;
* **failover of in-flight requests** — the headline property.  When a
  replica crashes, wedges, or is quarantined mid-decode, its unfinished
  requests are harvested and re-submitted on a sibling with
  ``replay=tokens_so_far``: the sibling re-prefills the prompt through
  the SAME shared executable and teacher-forces the already-delivered
  tokens (one decode step each, fused into its normal iteration), so a
  greedy stream continues BITWISE identically to an uninterrupted run
  and is never re-delivered.  Every accepted rid reaches a terminal
  ``finish_reason``;
* **supervised restart** — dead replicas are rebuilt cheaply: the
  compile-once program cache (``InferenceEngine._PROGRAMS``) is shared
  process-wide, so a restart allocates a fresh KV pool but never
  retraces (retrace counters stay flat — the bench asserts it).

Failure containment ladder: a poisoned SLOT is the engine watchdog's
job (that request alone retires "error" — and then the fleet retries it
on a sibling); a sick ENGINE is the fleet's job (quarantine + failover
+ supervised restart); only losing the whole process is left to the
layer above.

Hedged dispatch (``submit(..., hedge=True)``) duplicates a request onto
the two best replicas; the first terminal success wins and the loser is
cancelled — tail-latency insurance for critical requests (greedy
streams are identical on both, so the race is benign).

Usage::

    fleet = EngineFleet(ex, model, n_engines=3,
                        engine_kwargs=dict(n_slots=4, max_len=128))
    h = fleet.submit(prompt, max_new=64)       # -> FleetRequest
    fleet.wait([h]); print(h.result())
    fleet.rolling_restart()                    # zero accepted-rid loss
    fleet.stop()
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque

import numpy as np

from .. import telemetry as _telemetry
from .engine import InferenceEngine
from .health import (CircuitBreaker, DEGRADED, DISPATCHABLE, DRAINING,
                     HEALTH_STATE_CODES, HEALTHY, QUARANTINED,
                     ReplicaHealth, RESTARTING, STOPPED)
from .scheduler import EngineOverloaded, TERMINAL_OK

__all__ = ["EngineFleet", "FleetRequest", "FleetUnavailable"]

#: replica roles for disaggregated serving (EngineFleet(roles=...)):
#: "prefill" replicas admit + prefill and hand streams off, "decode"
#: replicas receive migrated streams, "mixed" (the default) does both.
_ROLES = ("prefill", "decode", "mixed")


class FleetUnavailable(RuntimeError):
    """No replica can take the request: every engine is circuit-broken,
    quarantined, draining, or stopped.  Mirrors ``ps.PSUnavailable`` —
    a TYPED terminal error carrying enough state to act on: ``states``
    maps each engine to its health state, and ``retry_after`` (seconds,
    or None when no breaker is counting down) aggregates the breaker
    backoffs into the soonest moment a retry could succeed."""

    def __init__(self, states, retry_after=None):
        hint = ("no restart pending" if retry_after is None
                else f"retry after ~{retry_after:.2f}s")
        super().__init__(
            f"fleet unavailable: no dispatchable replica ({states}; "
            f"{hint})")
        self.states = dict(states)
        self.retry_after = (None if retry_after is None
                            else float(retry_after))


class FleetRequest:
    """Cluster-level request handle.

    The engine-level :class:`~.scheduler.Request` is one ATTEMPT; this
    handle survives failover (same ``rid``, new attempt on a sibling)
    and is what client code holds.  ``tokens``/``result()`` always show
    the full stream from token 0 — a failed-over attempt replays its
    predecessor's tokens, so the latest attempt's token list IS the
    stream.  ``stream`` callbacks fire exactly once per token: replayed
    tokens are never re-delivered, and late emits from a superseded
    (wedged) attempt are fenced off."""

    def __init__(self, prompt, max_new, stream=None, eos_id=None,
                 deadline=None, arrival=None, hedge=False,
                 temperature=None, top_k=None, seed=None):
        self.rid = None             # set at first dispatch
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.stream_cb = stream
        self.eos_id = eos_id
        self.deadline = None if deadline is None else float(deadline)
        # per-request sampling (paged replicas): request-scoped, so a
        # failover re-dispatch samples under the SAME seed and the
        # continued stream stays bit-exact
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.hedge = bool(hedge)
        self.attempt = None         # current engine-level Request
        self.engine = None          # replica name serving the attempt
        self.engines = []           # replica names tried, in order
        self.failovers = 0
        self.hedge_attempt = None   # (replica_name, Request) secondary
        self.cancel_requested = False
        self.t_arrival = arrival
        self.t_done = None
        self._finished = False
        self._finish_reason = None
        self._tokens_snapshot = []  # last harvest fence (see fleet)

    @property
    def finished(self):
        return self._finished

    @property
    def finish_reason(self):
        return self._finish_reason

    @property
    def tokens(self):
        att = self.attempt
        return list(att.tokens) if att is not None \
            else list(self._tokens_snapshot)

    def result(self):
        return np.asarray(self.tokens, np.int32)

    def __repr__(self):
        state = ("done" if self._finished
                 else "live" if self.attempt is not None else "pending")
        return (f"FleetRequest(id={self.rid}, engine={self.engine}, "
                f"failovers={self.failovers}, {state})")


class _Replica:
    """One supervised engine slot: the engine, its driver thread, its
    health + breaker, and the fleet requests in flight on it."""

    def __init__(self, index, name, engine, health, breaker,
                 role="mixed"):
        self.index = index
        self.name = name
        self.engine = engine
        self.health = health
        self.breaker = breaker
        self.role = role           # "prefill" | "decode" | "mixed"
        self.lock = threading.RLock()
        self.thread = None
        self.generation = 0        # bumped to fence a zombie driver
        self.incarnation = 0       # restarts survived (rid uniqueness)
        self.inflight = {}         # rid -> (FleetRequest, attempt)
        self.dispatches = 0
        self.last_trips = 0        # engine.watchdog_trips at last tick
        self.last_error = None
        self.ttft_ewma = None
        self.tpot_ewma = None


class EngineFleet:
    """Health-checked multi-engine router with failover and supervised
    restart (see module doc).

    ``engine_kwargs`` is passed to every replica's
    :class:`~.engine.InferenceEngine` (n_slots, max_len, max_queue, …);
    the fleet itself supplies ``instance`` (cluster rids), ``clock``,
    ``latency_buckets``, and per-replica ``device`` pinning when the
    backend has multiple devices.  ``threaded=False`` disables the
    driver/supervisor threads: drive the fleet deterministically with
    :meth:`pump` (each tick is wedge-bounded: a stalled step is
    reported and quarantined when the pump regains control).

    ``engine_factory=`` swaps the replica type for any engine speaking
    the same surface (``submit``/``step``/``cancel``/``harvest``/
    ``scheduler``/``cache.audit``/``watchdog_trips``/``trace_counts``)
    — ``serving.embedding.EmbeddingServer`` rides the whole
    routing/health/failover machinery unchanged this way (a harvested
    embedding attempt delivered nothing, so it re-homes with an empty
    replay; read scores from ``freq.attempt.result()``)."""

    def __init__(self, executor, model, n_engines=2, engine_kwargs=None,
                 *, threaded=True, clock=None, name="fleet",
                 degraded_after=1, quarantine_after=3, recover_after=8,
                 breaker_base=0.25, breaker_cap=30.0, max_failovers=3,
                 wedge_timeout=None, wedge_floor=5.0, wedge_safety=50.0,
                 supervise_interval=0.02,
                 idle_sleep=0.001, auto_restart=True, ewma_alpha=0.3,
                 latency_buckets=None, engine_factory=None,
                 replica_prefix="e", tp_size=1, roles=None):
        if n_engines < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        # disaggregated prefill/decode: roles=("prefill", "decode", ...)
        # names one role per initial replica.  "prefill" replicas take
        # new submissions; once a stream has >= 1 generated token the
        # supervision pass migrates its pages to a "decode"/"mixed"
        # sibling (kv_transfer), so prefill-heavy replicas never spend
        # iterations decoding.  None (default) = every replica "mixed",
        # behavior unchanged.
        if roles is not None:
            roles = [str(r) for r in roles]
            if len(roles) != int(n_engines):
                raise ValueError(
                    f"roles has {len(roles)} entries for "
                    f"n_engines={n_engines}")
            bad = [r for r in roles if r not in _ROLES]
            if bad:
                raise ValueError(
                    f"unknown roles {bad}; expected one of {_ROLES}")
        self._roles = roles
        self._executor = executor
        self._model = model
        self._engine_factory = (InferenceEngine if engine_factory is None
                                else engine_factory)
        self._ekw = dict(engine_kwargs or {})
        self._ekw.pop("instance", None)
        self._ekw.pop("clock", None)
        self.name = str(name)
        self.threaded = bool(threaded)
        self._clock = clock if clock is not None else time.perf_counter
        self._hp = dict(degraded_after=degraded_after,
                        quarantine_after=quarantine_after,
                        recover_after=recover_after)
        self._bp = dict(base=breaker_base, cap=breaker_cap)
        self.max_failovers = int(max_failovers)
        # wedge_timeout=None derives the bound from the replica's
        # observed TPOT (effective_wedge_timeout); an explicit value is
        # an absolute override, as before
        self.wedge_timeout = (None if wedge_timeout is None
                              else float(wedge_timeout))
        self.wedge_floor = float(wedge_floor)
        self.wedge_safety = float(wedge_safety)
        self.supervise_interval = float(supervise_interval)
        self.idle_sleep = float(idle_sleep)
        self.auto_restart = bool(auto_restart)
        self.ewma_alpha = float(ewma_alpha)
        self._latency_buckets = latency_buckets
        # one replica per device when the mesh offers several (ROADMAP
        # direction 1's scale-out shape); on one device they time-share.
        # tp_size > 1 upgrades the unit of pinning from one device to a
        # contiguous group of tp_size devices: each replica becomes a
        # tensor-parallel engine on its own (replica=1, model=tp_size)
        # sub-mesh, and failover re-homes onto a sharded sibling
        import jax
        devs = jax.devices()
        self.tp_size = int(tp_size)
        if self.tp_size < 1:
            raise ValueError(f"tp_size must be >= 1, got {tp_size}")
        if self.tp_size > 1:
            if not self._ekw.get("paged"):
                raise ValueError(
                    "tp_size > 1 requires paged=True engine_kwargs — "
                    "the sharded executables are the paged pair")
            if len(devs) < self.tp_size:
                raise ValueError(
                    f"tp_size={self.tp_size} needs that many devices, "
                    f"have {len(devs)}")
            from . import sharding as _shd
            n_groups = len(devs) // self.tp_size
            self._meshes = [
                _shd.serving_mesh(
                    self.tp_size,
                    devices=devs[g * self.tp_size:(g + 1) * self.tp_size])
                for g in range(n_groups)]
            self._devices = [None]
        else:
            self._meshes = None
            self._devices = devs if len(devs) > 1 else [None] * n_engines
        self._requests = {}        # rid -> FleetRequest (accepted ever)
        self._flock = threading.Lock()
        # (FleetRequest, tokens, blob) to re-home: blob is the donor's
        # kv_transfer snapshot when one could be taken (page migration
        # first), None otherwise (teacher-forced replay only)
        self._failover = deque()
        self._cancels = deque()    # (replica_name, rid) deferred cancels
        self._prefix_handoffs = deque()   # (donor_name, prefix blob)
        # test/fault hook (resilience/faults.py): every migration blob
        # passes through this callable on its way to the receiver; None
        # return = dropped in flight, mutated bytes = corruption — the
        # CRC framing catches it and replay takes over
        self.transfer_filter = None
        self._migrate_lock = threading.Lock()   # one migration at a time
        # manual-mode dispatch-wedge watcher (armed around pump ticks)
        self._watch_armed = None
        self._watch_thread = None
        self._running = False
        self._sup_thread = None
        self.submitted = 0
        self.completed = 0
        self.failovers_done = 0
        self.migrations_done = 0
        self.migration_failures = 0
        self.prefix_handoffs_done = 0
        self.hedged = 0
        self.hedges_skipped = 0
        self.replica_prefix = str(replica_prefix)
        self._next_index = int(n_engines)   # add_replica allocation
        self.finish_counts = {}   # reason -> count (O(1) controller read)
        reg = _telemetry.get_registry()
        self._m_health = reg.gauge(
            "hetu_fleet_engine_health_state",
            "Replica health (0 healthy, 1 degraded, 2 quarantined, "
            "3 restarting, 4 draining, 5 stopped)", labels=("engine",))
        self._m_dispatch = reg.counter(
            "hetu_fleet_dispatches_total",
            "Requests routed to each replica", labels=("engine",))
        self._m_failovers = reg.counter(
            "hetu_fleet_failovers_total",
            "In-flight requests re-homed onto a sibling replica")
        self._m_breaker = reg.counter(
            "hetu_fleet_breaker_opens_total",
            "Circuit-breaker opens (quarantines)", labels=("engine",))
        self._m_restarts = reg.counter(
            "hetu_fleet_restarts_total",
            "Supervised replica restarts", labels=("engine",))
        self._m_drains = reg.counter(
            "hetu_fleet_drains_total",
            "Replica drains requested", labels=("engine",))
        self._m_crashes = reg.counter(
            "hetu_fleet_engine_crashes_total",
            "Driver-observed engine exceptions", labels=("engine",))
        self._m_wedges = reg.counter(
            "hetu_fleet_engine_wedges_total",
            "Stale-heartbeat quarantines (wedged step)",
            labels=("engine",))
        self._m_hedged = reg.counter(
            "hetu_fleet_hedged_dispatches_total",
            "Requests duplicated onto a second replica")
        self._m_unavail = reg.counter(
            "hetu_fleet_unavailable_total",
            "Submits refused with FleetUnavailable")
        self._m_migrations = reg.counter(
            "hetu_migrate_attempts_total",
            "Live KV page migrations attempted, by path (failover, "
            "rebalance, drain, handoff)", labels=("path",))
        self._m_migrate_fail = reg.counter(
            "hetu_migrate_failures_total",
            "Migrations that fell back to teacher-forced replay "
            "(torn/corrupt transfer, geometry drift, receiver refusal)",
            labels=("path",))
        self._m_migrate_bytes = reg.counter(
            "hetu_migrate_bytes_total",
            "Wire bytes of successfully spliced KV transfer blobs")
        self._m_migrate_prefix = reg.counter(
            "hetu_migrate_prefix_entries_total",
            "Prefix-cache entries re-interned on a sibling after their "
            "replica was quarantined")
        self._m_handoffs = reg.counter(
            "hetu_serving_role_handoffs_total",
            "Prefill->decode stream handoffs between role groups")
        self._g_role = reg.gauge(
            "hetu_serving_role_replicas",
            "Replicas per disaggregation role",
            labels=("fleet", "role"))
        self._rt = _telemetry.get_request_trace()
        self._fl = _telemetry.get_flight()
        self._tr = _telemetry.get_tracer()
        # multi-replica-per-chip param sharing: one placed copy of the
        # weights per device, every co-resident replica reads it —
        # device -> (placed pytree, HBM ledger handle, pool="params")
        self._param_store = {}
        self._replicas = [self._make_replica(i) for i in range(n_engines)]
        self._sync_role_gauge()
        self.start()

    # -- construction ------------------------------------------------------
    def _instance_name(self, index, incarnation):
        base = f"{self.replica_prefix}{index}"
        return base if incarnation == 0 else f"{base}.{incarnation}"

    def _shared_params(self, dev):
        """One placed copy of the weights per device, shared by every
        replica pinned there (and by every incarnation across
        restarts): N co-resident replicas cost 1x params HBM, not Nx.
        The copy is ledger-accounted once under ``pool="params"`` — the
        kv pools stay per-replica, so the incident-dump HBM view shows
        exactly what is deduplicated and what is not."""
        ent = self._param_store.get(dev)
        if ent is None:
            if dev is None:
                placed = self._executor.params
            else:
                import jax
                placed = {k: jax.device_put(v, dev)
                          for k, v in self._executor.params.items()}
            nbytes = sum(int(v.nbytes) for v in placed.values())
            handle = _telemetry.get_hbm_ledger().alloc(
                "params", nbytes,
                owner=f"fleet:{self.name}:params:{dev or 'host'}")
            ent = self._param_store[dev] = (placed, handle)
        return ent[0]

    def _build_engine(self, index, incarnation):
        if self._meshes is not None:
            # sub-mesh pinning: replicas round-robin the contiguous
            # device groups (same group across restarts — the rebuilt
            # engine reuses the incarnation-independent index, so the
            # compile-once cache keyed on device ids still hits)
            pin = dict(mesh=self._meshes[index % len(self._meshes)])
        else:
            # single-device pinning: the replica reads the fleet's
            # per-device shared copy of the params instead of placing
            # its own (engine_factory overrides — embedding fleets —
            # keep their own placement path)
            dev = self._devices[index % len(self._devices)]
            pin = dict(device=dev)
            if self._engine_factory is InferenceEngine:
                pin["shared_params"] = self._shared_params(dev)
        return self._engine_factory(
            self._executor, self._model,
            instance=self._instance_name(index, incarnation),
            clock=self._clock,
            latency_buckets=self._latency_buckets,
            **pin, **self._ekw)

    def _role_for(self, index):
        """Initial replicas get their configured role; replicas added
        later (controller scale-up) join as "mixed" — they can absorb
        whatever the fleet is short of."""
        if self._roles is not None and index < len(self._roles):
            return self._roles[index]
        return "mixed"

    @property
    def _has_roles(self):
        return self._roles is not None

    def _sync_role_gauge(self):
        counts = {r: 0 for r in _ROLES}
        for rep in self._replicas:
            counts[rep.role] = counts.get(rep.role, 0) + 1
        for role, n in counts.items():
            self._g_role.labels(fleet=self.name, role=role).set(n)

    def _make_replica(self, index):
        name = f"{self.replica_prefix}{index}"
        rep = _Replica(
            index, name, self._build_engine(index, 0),
            ReplicaHealth(name, clock=self._clock, **self._hp),
            CircuitBreaker(clock=self._clock, **self._bp),
            role=self._role_for(index))
        self._m_health.labels(engine=name).set(HEALTH_STATE_CODES[HEALTHY])
        return rep

    # -- elastic scale (the controller's actuators) ------------------------
    def add_replica(self):
        """Scale up: build one fresh replica at the next free index
        (indices are never reused, so rids stay unique across the
        fleet's whole life) and start its driver when threaded.
        Returns the new replica's name."""
        index = self._next_index
        self._next_index += 1
        rep = self._make_replica(index)
        # atomic list swap: readers iterate a snapshot, never a
        # half-mutated list
        self._replicas = self._replicas + [rep]
        self._sync_role_gauge()
        if self.threaded and self._running:
            self._start_driver(rep)
        return rep.name

    def remove_replica(self, name, wait=True, timeout=60.0):
        """Scale down with zero accepted-rid loss: drain the replica
        (siblings keep serving), then drop it from the fleet.  With
        ``wait=False`` the replica is left DRAINING and the call
        returns ``False``; call again once a later pump/supervise pass
        has drained it (the controller's two-phase scale-down).  The
        last replica cannot be removed."""
        rep = self._by_name(name, required=True)
        if len(self._replicas) <= 1:
            raise ValueError("cannot remove the last replica")
        if rep.health.state not in (QUARANTINED, STOPPED):
            self.drain(name, wait=wait, timeout=timeout)
        if rep.health.state not in (QUARANTINED, STOPPED):
            return False            # still draining (wait=False path)
        # QUARANTINED work was already harvested into the failover
        # queue; STOPPED means drained-to-idle — either way nothing of
        # ours runs there any more
        rep.generation += 1         # fence any driver thread
        if rep.health.state != STOPPED:
            rep.health.to(STOPPED, "removed")
        self._set_health(rep)
        if rep.engine is not None:
            rep.engine.close()
        self._replicas = [r for r in self._replicas if r is not rep]
        self._sync_role_gauge()
        return True

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Start driver + supervisor threads (no-op when already running
        or ``threaded=False``)."""
        if self._running:
            return self
        self._running = True
        if self.threaded:
            for rep in self._replicas:
                self._start_driver(rep)
            self._sup_thread = threading.Thread(
                target=self._supervise_loop, daemon=True,
                name=f"{self.name}-supervisor")
            self._sup_thread.start()
        return self

    def _start_driver(self, rep):
        rep.thread = threading.Thread(
            target=self._drive, args=(rep, rep.generation), daemon=True,
            name=f"{self.name}-{rep.name}-driver")
        rep.thread.start()

    def stop(self, finalize_pending=True):
        """Stop drivers + supervisor (joined; wedged zombies are fenced
        and abandoned as daemons).  Pending failovers that never found a
        home finalize with ``finish_reason="error"`` unless told not
        to."""
        self._running = False
        threads = [self._sup_thread, self._watch_thread] \
            + [r.thread for r in self._replicas]
        for rep in self._replicas:
            rep.generation += 1       # fence every driver
        for t in threads:
            if t is not None:
                t.join(timeout=2.0)
        self._sup_thread = None
        self._watch_thread = None
        if finalize_pending:
            with self._flock:
                pending, self._failover = list(self._failover), deque()
            for freq, *_ in pending:
                self._finalize(freq, "error")
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- dispatch ----------------------------------------------------------
    def _score(self, rep):
        """Latency-aware routing score: expected time for a NEW request
        to clear this replica — (waiting + running + itself) iterations
        at the replica's observed decode rate.  Unknown TPOT borrows the
        fleet mean so cold replicas aren't shunned."""
        sch = rep.engine.scheduler
        depth = len(sch.queue) + len(sch.running)
        known = [r.tpot_ewma for r in self._replicas
                 if r.tpot_ewma]
        default = sum(known) / len(known) if known else 1.0
        tpot = rep.tpot_ewma if rep.tpot_ewma else default
        return (depth + 1.0) * tpot

    def _candidates(self):
        return [r for r in self._replicas
                if r.health.dispatchable and r.engine is not None]

    def _choose(self, prefer_not=None, exclude=(), prompt=None,
                roles=None, strict_roles=False):
        cands = [r for r in self._candidates() if r.name not in exclude]
        if roles is not None and cands:
            # role preference: fall back to ANY dispatchable replica
            # unless strict (a role-pure handoff that has no valid
            # target should just not happen, not bounce) — no request
            # is ever refused because the "right" role is down
            wanted = [r for r in cands if r.role in roles]
            cands = wanted if (wanted or strict_roles) else cands
        if not cands:
            return None
        if prefer_not is not None and len(cands) > 1:
            others = [r for r in cands if r.name != prefer_not]
            cands = others or cands
        if prompt is not None and len(cands) > 1:
            # prefix-affinity tie-break: prefix caches are per-replica
            # (page ids are pool-local), so a prompt whose prefix some
            # replica already holds prefills fastest THERE — route to
            # the longest hit unless that replica is meaningfully more
            # loaded (>2x the best latency score; load still wins)
            hits, floor = {}, None
            for r in cands:
                fn = getattr(r.engine, "prefix_hit_tokens", None)
                hits[r.name] = int(fn(prompt)) if fn is not None else 0
            if any(hits.values()):
                floor = 2.0 * min(self._score(r) for r in cands)
                best = max(hits.values())
                warm = [r for r in cands
                        if hits[r.name] == best
                        and self._score(r) <= floor]
                cands = warm or cands
        return min(cands,
                   key=lambda r: (self._score(r), r.dispatches, r.name))

    def _unavailable(self, now=None, count=True):
        now = self._clock() if now is None else now
        states = {r.name: r.health.state for r in self._replicas}
        waits = [r.breaker.retry_after(now) for r in self._replicas
                 if r.health.state in (QUARANTINED, RESTARTING)]
        if count:
            self._m_unavail.inc()
            self._fl.incident("fleet_unavailable", health=self.health(),
                              extra={"states": dict(states)})
        return FleetUnavailable(states,
                                min(waits) if waits else None)

    def _wrap_stream(self, freq):
        if freq.stream_cb is None:
            return None

        def cb(tok, attempt_req):
            # fence: only the CURRENT attempt delivers — a superseded
            # (wedged/failed-over) attempt's late emits are dropped, and
            # replayed tokens never reach here (the engine absorbs them)
            if freq.finished or freq.attempt is not attempt_req:
                return
            freq.stream_cb(int(tok), freq)

        return cb

    def _submit_on(self, rep, freq, replay=None, secondary=False):
        """Dispatch (or re-dispatch) one fleet request onto a replica.
        Caller picked ``rep``; raises EngineOverloaded through."""
        # sampling kwargs ride along only when set: LLM engines accept
        # them (paged ones honor them), EmbeddingServer fleets never
        # see unexpected keywords
        kw = {k: getattr(freq, k) for k in ("temperature", "top_k",
                                            "seed")
              if getattr(freq, k, None) is not None}
        with rep.lock:
            attempt = rep.engine.submit(
                freq.prompt, freq.max_new,
                stream=self._wrap_stream(freq), eos_id=freq.eos_id,
                deadline=freq.deadline, replay=replay, rid=freq.rid,
                **kw)
            rep.inflight[attempt.rid] = (freq, attempt)
            rep.dispatches += 1
        if secondary:
            freq.hedge_attempt = (rep.name, attempt)
        else:
            freq.attempt = attempt
            freq.engine = rep.name
            if freq.rid is None:
                freq.rid = attempt.rid
        freq.engines.append(rep.name)
        self._m_dispatch.labels(engine=rep.name).inc()
        return attempt

    def submit(self, prompt, max_new, stream=None, eos_id=None,
               ttl=None, deadline=None, hedge=False, temperature=None,
               top_k=None, seed=None):
        """Route one request to the best replica; returns its
        :class:`FleetRequest`.  Raises :class:`FleetUnavailable` when no
        replica is dispatchable, or the last replica's
        :class:`~.scheduler.EngineOverloaded` when every dispatchable
        replica refused admission (the cluster is full, not down).
        ``hedge=True`` duplicates onto the second-best replica too —
        first terminal success wins, the loser is cancelled."""
        now = self._clock()
        if ttl is not None:
            if deadline is not None:
                raise ValueError("pass ttl= or deadline=, not both")
            if ttl <= 0:
                raise ValueError(f"ttl must be > 0, got {ttl}")
            deadline = now + float(ttl)
        freq = FleetRequest(prompt, max_new, stream=stream,
                            eos_id=eos_id, deadline=deadline,
                            arrival=now, hedge=hedge,
                            temperature=temperature, top_k=top_k,
                            seed=seed)
        # role routing: new work lands on prefill/mixed replicas;
        # decode-role replicas receive migrated streams (with graceful
        # fallback inside _choose when no prefill replica is up)
        rep = self._place(freq, now=now,
                          roles=(("prefill", "mixed") if self._has_roles
                                 else None))
        self._requests[freq.rid] = freq
        self.submitted += 1
        if hedge:
            second = self._choose(exclude={rep.name})
            if second is not None:
                try:
                    self._submit_on(second, freq, secondary=True)
                    self.hedged += 1
                    self._m_hedged.inc()
                except EngineOverloaded:
                    # hedging is best-effort insurance: the primary is
                    # already placed, so a full second replica only
                    # costs the duplicate — record and move on
                    self.hedges_skipped += 1
        return freq

    def _place(self, freq, now=None, prefer_not=None, replay=None,
               count_unavailable=True, roles=None):
        """Dispatch onto the best replica, falling through overloaded
        ones (each replica is tried at most once — the loop is bounded
        by the fleet size).  Raises the last EngineOverloaded when every
        dispatchable replica is full, FleetUnavailable when none is
        dispatchable at all; returns the replica on success.
        ``count_unavailable=False`` keeps internal retries (failover
        parking) out of the client-facing refusal counter."""
        tried, last_overload = set(), None
        for _ in range(len(self._replicas)):
            rep = self._choose(prefer_not=prefer_not, exclude=tried,
                               prompt=freq.prompt, roles=roles)
            if rep is None:
                break
            try:
                self._submit_on(rep, freq, replay=replay)
                return rep
            except EngineOverloaded as e:
                tried.add(rep.name)
                last_overload = e
        if last_overload is not None:
            raise last_overload
        raise self._unavailable(now, count=count_unavailable)

    def cancel(self, rid):
        """Cancel the live fleet request with this rid on whichever
        replica(s) hold an attempt (or in the failover queue).  Returns
        True if a live request was found."""
        freq = self._requests.get(rid)
        if freq is None or freq.finished:
            return False
        freq.cancel_requested = True
        hit = False
        for rep in self._replicas:
            if rid in rep.inflight and rep.engine is not None:
                with rep.lock:
                    hit = rep.engine.cancel(rid) or hit
        with self._flock:
            for i, (f, *_) in enumerate(self._failover):
                if f is freq:
                    del self._failover[i]
                    self._finalize(freq, "cancelled")
                    hit = True
                    break
        if not self.threaded:
            self._reap_all()
        return hit

    # -- the drive loop ----------------------------------------------------
    def _drive(self, rep, gen):
        while self._running and rep.generation == gen:
            busy = self._tick(rep, gen)
            if not busy:
                time.sleep(self.idle_sleep)

    def _tick(self, rep, gen=None):
        """One driver pass over a replica: heartbeat, one engine
        iteration, then fault/terminal bookkeeping.  Returns True when
        the replica did work."""
        gen = rep.generation if gen is None else gen
        rep.health.heartbeat()
        actions = None
        with rep.lock:
            if rep.generation != gen:
                return False
            state = rep.health.state
            if state in (QUARANTINED, RESTARTING, STOPPED):
                return False
            if rep.engine.scheduler.idle:
                if state == DRAINING:
                    rep.health.to(STOPPED, "drained")
                    self._set_health(rep)
                busy = False
                actions = self._reap_locked(rep)
            else:
                try:
                    rep.engine.step()
                except Exception as e:      # engine crash
                    if rep.generation != gen:
                        return False
                    actions = self._on_crash_locked(rep, e)
                    busy = False
                else:
                    if rep.generation != gen:
                        return False
                    busy = True
                    actions = self._after_step_locked(rep)
        if actions:
            self._queue_failovers(actions)
        self._run_cancels()
        return busy

    def _set_health(self, rep):
        self._m_health.labels(engine=rep.name).set(
            HEALTH_STATE_CODES[rep.health.state])

    def _after_step_locked(self, rep):
        """Post-step bookkeeping under the replica lock: feed the health
        machine, quarantine on a trip streak (harvest + failover), and
        map finished attempts onto fleet terminals.  Returns requests
        needing a new home (dispatched OUTSIDE the lock — two drivers
        failing over toward each other must not deadlock)."""
        trips = rep.engine.watchdog_trips
        delta = trips - rep.last_trips
        rep.last_trips = trips
        state = rep.health.observe(delta)
        self._set_health(rep)
        if state == QUARANTINED:
            return self._quarantine_locked(
                rep, rep.health.last_reason or "watchdog trips")
        if (state == HEALTHY and rep.breaker.failures
                and rep.health.clean_ticks >= rep.health.recover_after):
            rep.breaker.close()     # probation served: reset the backoff
        return self._reap_locked(rep)

    def _reap_locked(self, rep):
        """Map finished engine-level attempts to fleet-level outcomes."""
        failovers = []
        for rid in [r for r, (_, a) in rep.inflight.items()
                    if a.finished]:
            freq, attempt = rep.inflight.pop(rid)
            reason = attempt.finish_reason
            if freq.finished or reason == "failover":
                continue    # hedge loser / already harvested
            if reason in TERMINAL_OK:
                if freq.attempt is not attempt:
                    # hedge secondary finished first: promote it
                    freq.attempt = attempt
                    freq.engine = rep.name
                self._update_ewma(rep, attempt)
                self._finalize(freq, reason, cancel_others=True)
            elif reason in ("deadline", "cancelled"):
                if freq.attempt is attempt:
                    if reason == "cancelled" and not freq.cancel_requested:
                        # engine-side cancel the fleet didn't ask for
                        # (shouldn't happen) — treat as an error attempt
                        failovers.extend(
                            self._failover_or_fail(freq, attempt))
                    else:
                        self._finalize(freq, reason, cancel_others=True)
                # a cancelled hedge loser needs nothing
            elif reason == "error":
                other = self._promote_survivor(freq, attempt)
                if not other:
                    failovers.extend(
                        self._failover_or_fail(freq, attempt))
        return failovers

    def _promote_survivor(self, freq, dead_attempt):
        """Hedged request lost one attempt: bind to the live one."""
        if freq.attempt is dead_attempt and freq.hedge_attempt:
            name, att = freq.hedge_attempt
            if not att.finished:
                freq.attempt, freq.engine = att, name
                freq.hedge_attempt = None
                return True
        if (freq.hedge_attempt
                and freq.hedge_attempt[1] is dead_attempt):
            freq.hedge_attempt = None
            return freq.attempt is not None \
                and not freq.attempt.finished
        return False

    def _failover_or_fail(self, freq, attempt, blob=None):
        """The attempt died: queue a re-home, or give up past the cap.
        ``blob`` is the donor's page snapshot when one was taken before
        harvest — the dispatcher tries to splice it into a sibling
        before falling back to teacher-forced replay."""
        freq.failovers += 1
        tokens = list(attempt.tokens)
        freq._tokens_snapshot = tokens
        freq.attempt = None         # fence late emits from the old one
        if freq.failovers > self.max_failovers:
            self._finalize(freq, "error")
            return []
        return [(freq, tokens, blob)]

    def _quarantine_locked(self, rep, reason, harvest=True):
        """Open the breaker and (when the engine is still callable)
        harvest every live request for failover.  Before the harvest
        frees anything, the replica's migratable decode state is
        snapshotted: page blobs ride the failover queue so streams
        splice onto a sibling instead of replaying, and the prefix
        cache is exported for re-interning elsewhere (the interned
        pages would otherwise die with this replica)."""
        rep.health.to(QUARANTINED, reason)
        self._set_health(rep)
        rep.breaker.open_()
        self._m_breaker.labels(engine=rep.name).inc()
        self._fl.incident("breaker_open", health=self.health(),
                          extra={"engine": rep.name, "why": reason})
        out = []
        if harvest and rep.engine is not None:
            blobs = self._snapshot_for_failover(rep)
            self._stash_prefix_handoff(rep)
            harvested = rep.engine.harvest()
            for req in harvested:
                entry = rep.inflight.pop(req.rid, None)
                if entry is None:
                    continue
                freq, attempt = entry
                if freq.finished:
                    continue
                if self._promote_survivor(freq, attempt):
                    continue    # hedged twin still live elsewhere
                out.extend(self._failover_or_fail(
                    freq, attempt, blobs.get(req.rid)))
            # anything else finished in the same iteration
            out.extend(self._reap_locked(rep))
        return out

    def _snapshot_for_failover(self, rep):
        """Page blobs for every migratable in-flight stream (rid ->
        blob), taken BEFORE harvest frees the pages.  Best-effort:
        anything that cannot snapshot just rides replay."""
        from . import kv_transfer as kvt
        blobs = {}
        eng = rep.engine
        sch = getattr(eng, "scheduler", None)
        if sch is None:
            return blobs
        for req in list(sch.running.values()):
            if not kvt.can_migrate(eng, req):
                continue
            try:
                blobs[req.rid] = kvt.snapshot_request(eng, req)
            except Exception as e:
                self._note_migrate_failure(
                    "failover", req.rid, rep.name, None, e)
        return blobs

    def _stash_prefix_handoff(self, rep):
        """Export the quarantined replica's interned prefix pages; a
        later supervision pass re-interns them on the healthiest
        sibling (outside any replica lock)."""
        from . import kv_transfer as kvt
        try:
            blob = kvt.snapshot_prefix_cache(rep.engine)
        except Exception as e:
            self._note_migrate_failure("prefix", None, rep.name, None, e)
            return
        if blob is not None:
            with self._flock:
                self._prefix_handoffs.append((rep.name, blob))

    def _install_prefix_handoffs(self):
        """Drain stashed prefix-cache blobs into the best live sibling
        that runs a prefix cache (re-parked when none is up yet).  One
        bounded pass: each stashed blob is tried once; blobs stashed
        mid-pass wait for the next supervision tick."""
        from . import kv_transfer as kvt
        with self._flock:
            pending = list(self._prefix_handoffs)
            self._prefix_handoffs.clear()
        for i, (src_name, blob) in enumerate(pending):
            cands = [r for r in self._candidates()
                     if getattr(r.engine, "prefix_cache", None)
                     is not None and r.name != src_name]
            if not cands:
                with self._flock:
                    # re-park this and everything after it, in order,
                    # ahead of anything stashed while we worked
                    self._prefix_handoffs.extendleft(
                        reversed(pending[i:]))
                return
            dst = min(cands, key=lambda r: (self._score(r), r.name))
            try:
                with dst.lock:
                    n = kvt.install_prefix_cache(dst.engine, blob)
            except kvt.TransferError as e:
                self._note_migrate_failure(
                    "prefix", None, src_name, dst.name, e)
                continue
            self.prefix_handoffs_done += n
            if n:
                self._m_migrate_prefix.inc(n)

    def _note_migrate_failure(self, path, rid, src, dst, err):
        self.migration_failures += 1
        self._m_migrate_fail.labels(path=path).inc()
        self._fl.incident(
            "migrate_failed", health=self.health(),
            extra={"path": path, "rid": rid, "from": src, "to": dst,
                   "error": f"{type(err).__name__}: {err}"})

    def _on_crash_locked(self, rep, exc):
        rep.last_error = exc
        self._m_crashes.labels(engine=rep.name).inc()
        self._fl.incident(
            "engine_crash", health=self.health(),
            extra={"engine": rep.name,
                   "error": f"{type(exc).__name__}: {exc}"})
        warnings.warn(
            f"fleet {self.name}: engine {rep.name} crashed with "
            f"{type(exc).__name__}: {exc} — quarantined, in-flight "
            "requests failing over")
        return self._quarantine_locked(
            rep, f"engine crashed: {type(exc).__name__}")

    def _update_ewma(self, rep, attempt):
        a = self.ewma_alpha
        for field, val in (("ttft_ewma", attempt.ttft),
                           ("tpot_ewma", attempt.tpot)):
            if val is None:
                continue
            cur = getattr(rep, field)
            setattr(rep, field,
                    float(val) if cur is None
                    else (1.0 - a) * cur + a * float(val))

    def _finalize(self, freq, reason, cancel_others=False):
        if freq.finished:
            return
        freq._finished = True
        freq._finish_reason = reason
        freq.t_done = self._clock()
        self.completed += 1
        self.finish_counts[reason] = self.finish_counts.get(reason, 0) + 1
        if freq.rid is not None:
            # cluster-level terminal (idempotent over the engine-level
            # finish for healthy completions; the ONLY terminal for
            # requests that died in the failover queue)
            self._rt.event(freq.rid, "finish", engine=freq.engine,
                           reason=reason, cluster=True,
                           failovers=freq.failovers)
        if cancel_others and freq.hedge_attempt is not None:
            name, att = freq.hedge_attempt
            freq.hedge_attempt = None
            if not att.finished:
                self._cancels.append((name, att.rid))

    def _run_cancels(self):
        """Deferred cross-replica cancels (hedge losers): issued outside
        any other replica's lock to keep lock order acyclic."""
        while self._cancels:
            try:
                name, rid = self._cancels.popleft()
            except IndexError:
                return
            rep = self._by_name(name)
            if rep is None or rep.engine is None:
                continue
            with rep.lock:
                rep.inflight.pop(rid, None)
                rep.engine.cancel(rid)

    # -- failover + supervision --------------------------------------------
    def _queue_failovers(self, items):
        if not items:
            return
        with self._flock:
            self._failover.extend(items)
        if not self.threaded:
            self._dispatch_failovers()

    def _dispatch_failovers(self):
        """Re-home harvested requests: replay their tokens-so-far on the
        best sibling.  Requests that cannot be placed yet stay queued
        (the supervisor retries each pass); expired ones finalize.  One
        bounded pass over the queue snapshot per call."""
        with self._flock:
            pending, self._failover = list(self._failover), deque()
        for i, (freq, tokens, blob) in enumerate(pending):
            if freq.finished:
                continue
            now = self._clock()
            if freq.deadline is not None and now >= freq.deadline:
                self._finalize(freq, "deadline")
                continue
            # page migration first: splice the donor's snapshot into a
            # sibling's pool and the stream continues without replaying
            # a single token.  ANY transfer failure falls through to
            # replay — migration can only ever improve on it.
            if blob is not None and self._resume_from_blob(freq, blob):
                continue
            try:
                self._place(freq, now=now,
                            prefer_not=(freq.engines[-1]
                                        if freq.engines else None),
                            replay=tokens or None,
                            count_unavailable=False)
            except (EngineOverloaded, FleetUnavailable):
                # no home right now: park this and everything behind it
                # (order preserved) until capacity or a restart returns
                with self._flock:
                    self._failover.extendleft(reversed(pending[i:]))
                return
            self.failovers_done += 1
            self._m_failovers.inc()
            # the stitching seam: same cluster rid continues on the
            # sibling that _place just chose, replaying tokens-so-far
            self._rt.event(
                freq.rid, "failover_replay",
                engine=freq.engines[-1] if freq.engines else None,
                replayed=len(tokens),
                from_engine=(freq.engines[-2]
                             if len(freq.engines) > 1 else None))

    def _can_adopt(self, rep):
        """A migration target needs a FREE slot right now (adoption
        cannot queue the way replay-submit can) on a paged engine
        without a ModelDraft."""
        eng = rep.engine
        return (eng is not None and getattr(eng, "_paged", False)
                and eng._draft is None
                and len(eng.scheduler.running) < eng.cache.n_slots)

    def _resume_from_blob(self, freq, blob):
        """Try to re-home a harvested stream by splicing its page blob
        into the best sibling.  True on success; False (after counting
        the failure) sends the caller down the replay path.  The whole
        attempt — choose, wire, splice — runs under the ``kv_migrate``
        span either way: a dropped transfer spent its wire time too, and
        the goodput ledger's kv_migration bucket must see it."""
        from . import kv_transfer as kvt
        with self._tr.span("kv_migrate"):
            return self._resume_from_blob_inner(freq, blob, kvt)

    def _resume_from_blob_inner(self, freq, blob, kvt):
        last = freq.engines[-1] if freq.engines else None
        full = {r.name for r in self._replicas
                if not self._can_adopt(r)}
        rep = self._choose(prefer_not=last, exclude=full,
                           roles=(("decode", "mixed") if self._has_roles
                                  else None))
        if rep is None:
            return False    # nobody can adopt NOW: replay can queue
        self._m_migrations.labels(path="failover").inc()
        try:
            filt = self.transfer_filter
            wired = blob if filt is None else filt(blob)
            if wired is None:
                raise kvt.TransferError("transfer dropped in flight")
            with rep.lock:
                att = kvt.resume_request(rep.engine, wired,
                                         stream=self._wrap_stream(freq))
                rep.inflight[att.rid] = (freq, att)
                rep.dispatches += 1
                freq.attempt = att
                freq.engine = rep.name
        except kvt.TransferError as e:
            self._note_migrate_failure(
                "failover", freq.rid, last, rep.name, e)
            return False
        freq.engines.append(rep.name)
        self._m_dispatch.labels(engine=rep.name).inc()
        self.migrations_done += 1
        self._m_migrate_bytes.inc(len(blob))
        self.failovers_done += 1
        self._m_failovers.inc()
        self._rt.event(freq.rid, "migrated", engine=rep.name,
                       path="failover", bytes=len(blob),
                       from_engine=last)
        return True

    # -- live migration (both replicas up) ----------------------------------
    def _migrate_attempt(self, src, freq, att, dst, path):
        """Live-migrate one running stream from ``src`` to ``dst``:
        snapshot under the donor lock (the donor cannot step past the
        snapshot), splice into the receiver, rebind the stream fence,
        then ack the donor (which frees its pages).  Serialized
        fleet-wide by ``_migrate_lock`` so two replicas never migrate
        toward each other with crossed locks.  Returns True on success;
        on ANY transfer failure the stream stays on the donor untouched
        — migrating is strictly no worse than not migrating."""
        from . import kv_transfer as kvt
        if dst is None or dst is src:
            return False
        # "kv_migrate" span: snapshot + wire + splice + ack, including
        # the fleet-wide serialization wait — the goodput ledger's
        # kv_migration bucket (failed attempts count too: their time
        # was spent either way)
        with self._tr.span("kv_migrate"), self._migrate_lock:
            with src.lock:
                if src.engine is None or dst.engine is None:
                    return False
                if (freq.finished or freq.attempt is not att
                        or att.finished
                        or freq.hedge_attempt is not None
                        or not kvt.can_migrate(src.engine, att)):
                    return False
                self._m_migrations.labels(path=path).inc()
                try:
                    blob = kvt.snapshot_request(src.engine, att)
                    filt = self.transfer_filter
                    wired = blob if filt is None else filt(blob)
                    if wired is None:
                        raise kvt.TransferError(
                            "transfer dropped in flight")
                    with dst.lock:
                        new = kvt.resume_request(
                            dst.engine, wired,
                            stream=self._wrap_stream(freq))
                        dst.inflight[new.rid] = (freq, new)
                        dst.dispatches += 1
                        # rebind INSIDE the receiver lock: the stream
                        # fence flips to the new attempt before the
                        # receiver can deliver a single token
                        freq.attempt = new
                        freq.engine = dst.name
                except kvt.TransferError as e:
                    self._note_migrate_failure(
                        path, freq.rid, src.name, dst.name, e)
                    return False
                # donor ack: only now does the donor free its side —
                # the receiver already owns the adopted stream
                src.inflight.pop(freq.rid, None)
                src.engine.release_migrated(freq.rid)
        freq.engines.append(dst.name)
        self.migrations_done += 1
        self._m_migrate_bytes.inc(len(blob))
        self._m_dispatch.labels(engine=dst.name).inc()
        self._rt.event(freq.rid, "migrated", engine=dst.name,
                       path=path, bytes=len(blob),
                       from_engine=src.name)
        return True

    def migrate_out(self, name, path="drain", roles=None):
        """Preemptively move every migratable stream off ``name`` onto
        siblings (scale-down, maintenance: migrate-then-drain).
        Returns the number moved; whatever cannot move simply stays and
        drains normally — no stream is ever worse off for the try."""
        rep = self._by_name(name, required=True)
        if rep.engine is None:
            return 0
        if roles is None and self._has_roles:
            roles = ("decode", "mixed")
        moved = 0
        for rid, (freq, att) in list(rep.inflight.items()):
            if freq.finished or att.finished \
                    or freq.attempt is not att:
                continue
            full = {r.name for r in self._replicas
                    if not self._can_adopt(r)}
            dst = self._choose(exclude={rep.name} | full, roles=roles)
            if dst is None:
                break
            if self._migrate_attempt(rep, freq, att, dst, path):
                moved += 1
        return moved

    def rebalance(self, src, dst=None, max_requests=1,
                  path="rebalance"):
        """Move up to ``max_requests`` running decode streams off the
        ``src`` replica onto ``dst`` (or the best-scored sibling) — the
        SLO controller calls this to shed load from a hot replica
        without restarting anything.  Returns the number moved."""
        s = self._by_name(src, required=True)
        if s.engine is None:
            return 0
        moved = 0
        for rid, (freq, att) in list(s.inflight.items()):
            if moved >= int(max_requests):
                break
            if freq.finished or att.finished \
                    or freq.attempt is not att:
                continue
            full = {r.name for r in self._replicas
                    if not self._can_adopt(r)}
            d = (self._by_name(dst, required=True) if dst is not None
                 else self._choose(exclude={s.name} | full))
            if d is None or not self._can_adopt(d) \
                    or not d.health.dispatchable:
                break
            if self._migrate_attempt(s, freq, att, d, path):
                moved += 1
        return moved

    def _migration_pass(self):
        """Disaggregation pass (role fleets only): any decode stream
        still running on a prefill-role replica is handed off to a
        decode/mixed sibling as soon as one can take it — prefill
        replicas stay free to absorb new prompts, decode replicas own
        the long tail.  Runs every supervision pass / pump."""
        if not self._has_roles:
            return
        for rep in list(self._replicas):
            if rep.role != "prefill" or rep.engine is None \
                    or rep.health.state not in (HEALTHY, DEGRADED):
                continue
            self._handoff_from(rep)

    def _handoff_from(self, rep):
        for rid, (freq, att) in list(rep.inflight.items()):
            if freq.finished or att.finished \
                    or freq.attempt is not att:
                continue
            # strict: a role-pure handoff with no decode sibling up
            # should just not happen (keep decoding here), not bounce
            # to another prefill replica
            full = {r.name for r in self._replicas
                    if not self._can_adopt(r)}
            dst = self._choose(roles=("decode", "mixed"),
                               exclude={rep.name} | full,
                               strict_roles=True)
            if dst is None:
                return
            if self._migrate_attempt(rep, freq, att, dst, "handoff"):
                self._m_handoffs.inc()

    def _supervise_loop(self):
        while self._running:
            try:
                self._supervise_once()
            except Exception as e:      # supervisor must never die
                warnings.warn(
                    f"fleet {self.name}: supervisor error "
                    f"{type(e).__name__}: {e}")
            time.sleep(self.supervise_interval)

    def effective_wedge_timeout(self, rep=None):
        """The heartbeat-staleness bound that counts as a wedge.  An
        explicit ``wedge_timeout=`` kwarg is absolute; by default the
        bound is derived from the replica's OBSERVED decode rate —
        ``max(wedge_floor, wedge_safety × TPOT_EWMA)`` — so detection
        survives real TPU step times instead of assuming 5 s ≫ one
        step.  A replica with no TPOT yet borrows the slowest sibling's
        (conservative: slow siblings imply slow steps here too) and
        falls back to the floor before any EWMA exists."""
        if self.wedge_timeout is not None:
            return self.wedge_timeout
        tpot = rep.tpot_ewma if rep is not None else None
        if not tpot:
            known = [r.tpot_ewma for r in self._replicas if r.tpot_ewma]
            tpot = max(known) if known else 0.0
        return max(self.wedge_floor, self.wedge_safety * tpot)

    def _supervise_once(self):
        """One supervision pass: wedge detection (threaded only),
        breaker-gated restarts, failover dispatch, deferred cancels."""
        now = self._clock()
        for rep in list(self._replicas):
            if (self.threaded and rep.thread is not None
                    and rep.thread.is_alive()
                    and rep.health.state in (HEALTHY, DEGRADED)
                    and rep.engine is not None
                    and not rep.engine.scheduler.idle
                    and rep.health.heartbeat_age(now)
                    > self.effective_wedge_timeout(rep)):
                self._on_wedge(rep, rep.health.heartbeat_age(now))
            if (rep.health.state == QUARANTINED and self.auto_restart
                    and rep.breaker.allow(now)):
                self.restart(rep.name)
        self._migration_pass()
        self._install_prefix_handoffs()
        self._dispatch_failovers()
        self._run_cancels()

    def _on_wedge(self, rep, age):
        """A driver stuck inside ``step()`` (hung device call, stalled
        callback): fence it, harvest from a SNAPSHOT (the zombie holds
        the lock, so no clean retire — the engine is abandoned and
        replaced at restart), fail the requests over."""
        rep.generation += 1         # zombie exits when step returns
        self._m_wedges.labels(engine=rep.name).inc()
        self._fl.incident(
            "engine_wedge", health=self.health(),
            extra={"engine": rep.name, "heartbeat_age_s": round(age, 4)})
        warnings.warn(
            f"fleet {self.name}: engine {rep.name} heartbeat stale "
            f"{age:.2f}s — wedged; quarantining and failing over")
        inflight, rep.inflight = rep.inflight, {}
        out = []
        for rid, (freq, attempt) in inflight.items():
            if freq.finished:
                continue
            if self._promote_survivor(freq, attempt):
                continue
            # no clean engine-side harvest exists (the zombie driver
            # owns the engine) — mark the seam from the fleet side
            self._rt.event(rid, "harvested", engine=rep.name,
                           why="wedge")
            out.extend(self._failover_or_fail(freq, attempt))
        # lockless state flip: the zombie only touches the engine, and
        # every post-step path re-checks the generation fence
        rep.health.to(QUARANTINED, f"heartbeat stale {age:.2f}s")
        self._set_health(rep)
        rep.breaker.open_()
        self._m_breaker.labels(engine=rep.name).inc()
        rep.engine = None           # abandoned with the zombie
        self._queue_failovers(out)

    # -- restart / drain ---------------------------------------------------
    def restart(self, name):
        """Supervised restart: fence any old driver, rebuild the engine
        (fresh KV pool; the compile-once program cache is shared, so no
        retrace), and return the replica to HEALTHY.  The breaker keeps
        its failure streak until the replica proves itself with clean
        ticks — a crash loop backs off exponentially."""
        rep = self._by_name(name, required=True)
        if rep.inflight and rep.engine is not None \
                and rep.health.state not in (QUARANTINED, RESTARTING):
            # operator restart of a LIVE replica: fail its work over
            # first (an imposed quarantine), never drop bookkeeping
            with rep.lock:
                actions = self._quarantine_locked(rep,
                                                  "operator restart")
            self._queue_failovers(actions)
        rep.generation += 1
        rep.health.to(RESTARTING, "supervised restart")
        self._set_health(rep)
        rep.incarnation += 1
        # a wedged zombie may hold the old lock forever: new lock too
        rep.lock = threading.RLock()
        rep.engine = self._build_engine(rep.index, rep.incarnation)
        rep.last_trips = 0
        rep.inflight = {}
        rep.health.to(HEALTHY, "restarted")
        self._set_health(rep)
        self._m_restarts.labels(engine=rep.name).inc()
        if self.threaded and self._running:
            self._start_driver(rep)
        return rep.name

    def drain(self, name=None, wait=True, timeout=60.0, migrate=False):
        """Stop dispatching to the replica(s) but finish what they hold;
        DRAINING flips to STOPPED at idle.  ``wait=True`` blocks (or
        pumps, when ``threaded=False``) until drained.
        ``migrate=True`` first live-migrates every migratable decode
        stream to a sibling (scale-down: the long decode tail moves NOW
        instead of being waited out), then drains whatever remains."""
        reps = ([self._by_name(name, required=True)] if name is not None
                else list(self._replicas))
        for rep in reps:
            if rep.health.state in (QUARANTINED, RESTARTING, STOPPED):
                continue
            rep.health.to(DRAINING, "drain requested")
            self._set_health(rep)
            self._m_drains.labels(engine=rep.name).inc()
            if migrate:
                # flip DRAINING first (no new work lands mid-migration),
                # then move the tail; non-migratable streams just drain
                self.migrate_out(rep.name, path="drain")
        if wait:
            self._wait_for(
                lambda: all(r.health.state != DRAINING for r in reps),
                timeout, "drain")
        return self

    def rolling_restart(self, timeout=60.0):
        """Zero-accepted-loss rolling restart: drain each replica in
        turn (siblings keep serving), restart it, move on."""
        for rep in list(self._replicas):
            self.drain(rep.name, wait=True, timeout=timeout)
            self.restart(rep.name)
        return self

    # -- pumping / waiting -------------------------------------------------
    def pump(self, iterations=1):
        """Deterministic manual drive (``threaded=False`` fleets): one
        tick per replica per iteration, then one supervision pass.

        Each tick is bounded by the same wedge check the threaded
        supervisor runs: a step that stalls past
        :meth:`effective_wedge_timeout` has, by the time the pump loop
        regains control, already blocked the caller — it cannot be
        pre-empted from inside one thread, but it IS reported (wedge
        metric + incident) and the replica is quarantined + failed
        over instead of silently degrading every later iteration."""
        if self.threaded:
            raise RuntimeError(
                "pump() drives threaded=False fleets; this one runs "
                "driver threads")
        for _ in range(int(iterations)):
            for rep in list(self._replicas):
                busy = (rep.health.state in (HEALTHY, DEGRADED)
                        and rep.engine is not None
                        and not rep.engine.scheduler.idle)
                t0 = self._clock()
                if busy:
                    # arm the dispatch watcher BEFORE the tick: if this
                    # step wedges inside the device call, the caller is
                    # stuck and cannot report it — the watcher thread
                    # quarantines + fails over from the side instead
                    bound = self.effective_wedge_timeout(rep)
                    self._ensure_watcher()
                    self._watch_armed = (rep, rep.generation,
                                         time.perf_counter() + bound,
                                         bound)
                try:
                    self._tick(rep)
                finally:
                    self._watch_armed = None
                dur = self._clock() - t0
                if busy and dur > self.effective_wedge_timeout(rep) \
                        and rep.health.state in (HEALTHY, DEGRADED) \
                        and rep.engine is not None:
                    self._on_pump_stall(rep, dur)
            self._supervise_once()
        return self

    def _ensure_watcher(self):
        """Lazy dispatch watcher for manual (``threaded=False``)
        fleets: the pump loop arms a deadline before every busy tick,
        so a step that wedges INSIDE the dispatch is detected while the
        pumping caller is still stuck — the manual-mode mirror of the
        threaded supervisor's heartbeat check.  One daemon thread per
        fleet, started on first use, joined at stop()."""
        t = self._watch_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=self._watch_loop,
                             name=f"{self.name}-dispatch-watch",
                             daemon=True)
        self._watch_thread = t
        t.start()

    def _watch_loop(self):
        # wall-clock on purpose: a ManualClock fleet still wedges in
        # real time, and the stuck caller cannot advance any clock
        while self._running:
            armed = self._watch_armed
            if armed is not None:
                rep, gen, deadline, bound = armed
                if (time.perf_counter() >= deadline
                        and rep.generation == gen
                        and self._watch_armed is armed):
                    self._watch_armed = None
                    try:
                        self._on_dispatch_wedge(rep, gen, bound)
                    except Exception as e:   # watcher must never die
                        warnings.warn(
                            f"fleet {self.name}: dispatch watcher "
                            f"error {type(e).__name__}: {e}")
            time.sleep(min(self.supervise_interval, 0.005))

    def _on_dispatch_wedge(self, rep, gen, bound):
        """An armed pump tick blew past its wedge bound with the caller
        still stuck inside the dispatch: same fencing as a threaded
        wedge (:meth:`_on_wedge`), run from the watcher thread, tagged
        ``mode="dispatch"`` so operators can tell the two apart."""
        if rep.generation != gen \
                or rep.health.state not in (HEALTHY, DEGRADED):
            return
        rep.generation += 1     # fence: the stuck tick discards itself
        self._m_wedges.labels(engine=rep.name).inc()
        self._fl.incident(
            "engine_wedge", health=self.health(),
            extra={"engine": rep.name, "mode": "dispatch",
                   "wedge_timeout_s": round(bound, 4)})
        warnings.warn(
            f"fleet {self.name}: engine {rep.name} dispatch stuck past "
            f"{bound:.2f}s — wedged; quarantining and failing over")
        inflight, rep.inflight = rep.inflight, {}
        out = []
        for rid, (freq, attempt) in inflight.items():
            if freq.finished:
                continue
            if self._promote_survivor(freq, attempt):
                continue
            # the zombie dispatch owns the engine (and its pool): no
            # clean harvest, no page snapshot — replay is the seam
            self._rt.event(rid, "harvested", engine=rep.name,
                           why="wedge")
            out.extend(self._failover_or_fail(freq, attempt))
        rep.health.to(QUARANTINED,
                      f"dispatch stuck past {bound:.2f}s")
        self._set_health(rep)
        rep.breaker.open_()
        self._m_breaker.labels(engine=rep.name).inc()
        rep.engine = None           # abandoned with the stuck call
        self._queue_failovers(out)

    def _on_pump_stall(self, rep, dur):
        """A manual-mode tick stalled past the wedge bound.  Unlike a
        threaded wedge the step RETURNED (nobody holds the engine), so
        the replica is quarantined through the clean harvest path and
        its work failed over; auto_restart revives it through the
        breaker like any other quarantine."""
        self._m_wedges.labels(engine=rep.name).inc()
        self._fl.incident(
            "engine_wedge", health=self.health(),
            extra={"engine": rep.name, "stalled_step_s": round(dur, 4),
                   "mode": "pump"})
        warnings.warn(
            f"fleet {self.name}: engine {rep.name} pump tick stalled "
            f"{dur:.2f}s — wedged; quarantining and failing over")
        with rep.lock:
            actions = self._quarantine_locked(
                rep, f"pump tick stalled {dur:.2f}s")
        self._queue_failovers(actions)

    def _reap_all(self):
        """Manual-mode bookkeeping sweep without stepping engines."""
        for rep in self._replicas:
            if rep.engine is None:
                continue
            with rep.lock:
                actions = self._reap_locked(rep)
            self._queue_failovers(actions)

    @property
    def idle(self):
        with self._flock:
            if self._failover:
                return False
        for rep in self._replicas:
            if rep.health.state in (QUARANTINED, RESTARTING):
                continue        # harvested; nothing of ours runs there
            if rep.engine is not None \
                    and not rep.engine.scheduler.idle:
                return False
        return True

    def _wait_for(self, cond, timeout, what):
        if not self.threaded:
            it = 0
            while not cond():
                if it >= 100000:
                    raise RuntimeError(
                        f"fleet {what} did not complete in {it} pumps")
                self.pump()
                it += 1
            return
        deadline = time.perf_counter() + timeout
        while not cond():
            if time.perf_counter() >= deadline:
                raise TimeoutError(
                    f"fleet {what} did not complete within {timeout}s")
            time.sleep(self.idle_sleep)

    def wait(self, reqs=None, timeout=60.0):
        """Block (threaded) or pump (manual) until ``reqs`` (default:
        every accepted request) all reach a terminal finish_reason."""
        reqs = list(self._requests.values()) if reqs is None else reqs
        self._wait_for(lambda: all(r.finished for r in reqs), timeout,
                       "wait")
        return reqs

    def generate_many(self, prompts, max_new, eos_id=None, timeout=60.0):
        """Synchronous batch API across the fleet."""
        reqs = [self.submit(p, max_new, eos_id=eos_id) for p in prompts]
        self.wait(reqs, timeout=timeout)
        return [r.result() for r in reqs]

    # -- introspection -----------------------------------------------------
    def _by_name(self, name, required=False):
        for rep in self._replicas:
            if rep.name == name:
                return rep
        if required:
            raise KeyError(f"no replica named {name!r}")
        return None

    def health(self):
        """{engine: health snapshot} for every replica."""
        return {r.name: r.health.snapshot() for r in self._replicas}

    def audit(self):
        """Per-replica slot audit of every LIVE engine (a wedged
        engine's pool is abandoned with it and replaced at restart)."""
        return {r.name: r.engine.cache.audit()
                for r in self._replicas if r.engine is not None}

    def trace_counts(self):
        """The shared compile-once witness (max over live replicas —
        they share the program cache, so these are the same entry)."""
        out = {}
        for r in self._replicas:
            if r.engine is None:
                continue
            for k, v in r.engine.trace_counts.items():
                out[k] = max(out.get(k, 0), v)
        return out

    def stats(self):
        with self._flock:
            pending = len(self._failover)
        reasons = {}
        for freq in self._requests.values():
            if freq.finished:
                reasons[freq.finish_reason] = \
                    reasons.get(freq.finish_reason, 0) + 1
        return {
            "n_engines": len(self._replicas),
            "tp_size": self.tp_size,
            "submitted": self.submitted,
            "completed": self.completed,
            "failovers": self.failovers_done,
            "migrations": self.migrations_done,
            "migration_failures": self.migration_failures,
            "prefix_handoffs": self.prefix_handoffs_done,
            "hedged": self.hedged,
            "hedges_skipped": self.hedges_skipped,
            "pending_failovers": pending,
            "finish_reasons": reasons,
            "trace_counts": self.trace_counts(),
            "engines": {
                r.name: {
                    "state": r.health.state,
                    "role": r.role,
                    "incarnation": r.incarnation,
                    "dispatches": r.dispatches,
                    "ttft_ewma": r.ttft_ewma,
                    "tpot_ewma": r.tpot_ewma,
                    "breaker_opens": r.breaker.opens,
                    "breaker_failures": r.breaker.failures,
                    "engine": (None if r.engine is None
                               else r.engine.stats()),
                } for r in self._replicas},
        }
