"""Continuous-batching inference serving over the KV-cache decoders.

Slot-pooled K/V cache — dense per-slot spans (``SlotKVCache``) or a
paged pool with per-slot block tables, batched + chunked prefill, and
per-request sampling operands (``PagedKVCache``, ``paged=True`` on the
engine; docs/SERVING.md walks the page math) — plus an
iteration-level FIFO scheduler
with bounded-queue admission control (scheduler.py) + slot-batched
model adapters (adapters.py) + the engine tying them together with
per-request deadlines, cancellation, and a decode watchdog (engine.py).
Above the single engine sits the FLEET layer (fleet.py + health.py): N
supervised engine replicas behind a latency-aware router with health
state machines, circuit-broken quarantine, failover of in-flight
requests (bitwise-identical greedy streams via teacher-forced replay),
and supervised restarts over the shared compile-once program cache.
``bench.py --serve`` replays a Poisson arrival trace through the engine
and its static-batch twin; ``bench.py --chaos --serve`` injects serving
faults and proves one engine survives them; ``bench.py --chaos --serve
--fleet`` kills, wedges, and rolls whole replicas and proves the fleet
loses nothing.

Engines scale past one chip with TENSOR-PARALLEL serving (sharding.py,
docs/SHARDING.md): ``InferenceEngine(..., paged=True, mesh=
serving_mesh(tp))`` shards block weights on their output dims and the
KV page pool over kv_heads, with activations gathered back to
replicated before every cross-shard reduction — so the sharded engine
is a token-stream-bitwise twin of the single-chip one, and
``EngineFleet(tp_size=N)`` pins one replica per contiguous N-device
sub-mesh with failover replay landing bit-exactly on a sharded sibling.

The paged engine's raw-speed multiplier is SPECULATIVE DECODING
(speculative.py): a cheap draft — truncated-layer self-draft or an
injectable small model — proposes ``spec_k`` tokens per iteration and
ONE fused verify step teacher-forces the whole window, committing the
accepted prefix bitwise-identically to the non-speculative twin (the
replay path widened to ``[S, k+1]``; rejected rows roll back by
host-side position bookkeeping alone).  On the same page refcounts,
PREFIX CACHING (prefix_cache.py) interns finished prompts' page-aligned
prefixes and shares them into later admissions — shared system prompts
skip prefill, guarded read-only with copy-on-write forking.

A second production workload rides the same lifecycle: the embedding
subpackage (embedding/) serves batched sparse-feature lookups + CTR
scoring through the identical Scheduler — a HET-style device hot-row
cache over the PS table tier, packed-lookup scoring, and
``EngineFleet(engine_factory=EmbeddingServer)`` for cluster routing.
``bench.py --serve-embed`` replays a seeded Zipfian key trace against
an uncached host-tier twin.

The fleet also moves LIVE state between replicas (kv_transfer.py): a
mid-decode request's refcounted KV pages — raw float32 rows or the
quantized pool's codes + scales — serialize into a CRC32-framed blob
that splices into a sibling's pool and continues the stream BITWISE
where it left off (paged sampling keys fold only the per-request seed
and consumed count).  Four robustness paths ride the wire:
prefill→decode handoff in role-split fleets (``EngineFleet(roles=)``),
page-level failover after a crash, SLO-driven decode rebalancing
(``fleet.rebalance``), and migrate-then-drain scale-down
(``drain(migrate=True)``); the quarantined replica's prefix cache is
re-interned on a sibling the same way.  Any transfer failure — torn or
corrupt frame, geometry drift, a full receiver — raises
:class:`~.kv_transfer.TransferError` and the fleet falls back to
teacher-forced replay, so migration is strictly no worse than the
PR 12 failover oracle.

Above the fleet sits the SLO control plane (control.py): a declared
:class:`~.control.SLO` plus a :class:`~.control.FleetController` that
autoscales replicas, sheds provably-infeasible work at admission with a
typed :class:`~.control.SLOReject`, and walks a staged brownout ladder
under sustained violation.  ``bench.py --slo`` replays a bursty diurnal
trace through a controlled fleet vs its static twin.
"""

from .kv_cache import PagedKVCache, QuantizedKVPool, SlotKVCache
from .scheduler import (EngineOverloaded, Request, Scheduler,
                        FINISH_REASONS, SHED_POLICIES, TERMINAL_OK)
from .adapters import (LlamaSlotAdapter, GPTSlotAdapter, adapter_for)
from .engine import InferenceEngine
from .speculative import ModelDraft, SelfDraft
from .prefix_cache import PrefixCache
from .sharding import (KV_POOL_SPEC, kv_sharding, param_pspecs,
                       param_shardings, per_chip_bytes, serving_mesh,
                       shard_params, validate_tp)
from .health import (CircuitBreaker, ReplicaHealth, HEALTH_STATES,
                     HEALTH_STATE_CODES)
from .fleet import EngineFleet, FleetRequest, FleetUnavailable
from .kv_transfer import (TransferError, blob_info, can_migrate,
                          install_prefix_cache, resume_request,
                          snapshot_prefix_cache, snapshot_request)
from .control import (CostModel, DEGRADE_LEVELS, FleetController, SLO,
                      SLOReject)
from .embedding import (BatchSlotPool, DeviceHotRowCache, EmbedRequest,
                        EmbeddingServer, EMBED_BUCKETS)

__all__ = ["PagedKVCache", "QuantizedKVPool", "SlotKVCache",
           "Request", "Scheduler",
           "EngineOverloaded",
           "FINISH_REASONS", "SHED_POLICIES", "TERMINAL_OK",
           "LlamaSlotAdapter", "GPTSlotAdapter", "adapter_for",
           "InferenceEngine", "ModelDraft", "SelfDraft", "PrefixCache",
           "CircuitBreaker", "ReplicaHealth",
           "HEALTH_STATES", "HEALTH_STATE_CODES", "EngineFleet",
           "FleetRequest", "FleetUnavailable", "TransferError",
           "blob_info", "can_migrate", "install_prefix_cache",
           "resume_request", "snapshot_prefix_cache",
           "snapshot_request", "CostModel",
           "DEGRADE_LEVELS", "FleetController", "SLO", "SLOReject",
           "BatchSlotPool", "DeviceHotRowCache", "EmbedRequest",
           "EmbeddingServer", "EMBED_BUCKETS", "KV_POOL_SPEC",
           "kv_sharding", "param_pspecs", "param_shardings",
           "per_chip_bytes", "serving_mesh", "shard_params",
           "validate_tp"]
