"""Continuous-batching inference serving over the KV-cache decoders.

Slot-pooled K/V cache (kv_cache.py) + iteration-level FIFO scheduler
with bounded-queue admission control (scheduler.py) + slot-batched
model adapters (adapters.py) + the engine tying them together with
per-request deadlines, cancellation, and a decode watchdog (engine.py).
``bench.py --serve`` replays a Poisson arrival trace through the engine
and its static-batch twin; ``bench.py --chaos --serve`` injects serving
faults (poisoned decode, raising step, slot leaks, stalled consumers,
arrival bursts) and proves the engine survives them.
"""

from .kv_cache import SlotKVCache
from .scheduler import (EngineOverloaded, Request, Scheduler,
                        FINISH_REASONS, SHED_POLICIES)
from .adapters import (LlamaSlotAdapter, GPTSlotAdapter, adapter_for)
from .engine import InferenceEngine

__all__ = ["SlotKVCache", "Request", "Scheduler", "EngineOverloaded",
           "FINISH_REASONS", "SHED_POLICIES", "LlamaSlotAdapter",
           "GPTSlotAdapter", "adapter_for", "InferenceEngine"]
