"""Continuous-batching inference serving over the KV-cache decoders.

Slot-pooled K/V cache (kv_cache.py) + iteration-level FIFO scheduler
(scheduler.py) + slot-batched model adapters (adapters.py) + the
engine tying them together (engine.py).  ``bench.py --serve`` replays a
Poisson arrival trace through the engine and its static-batch twin.
"""

from .kv_cache import SlotKVCache
from .scheduler import Request, Scheduler
from .adapters import (LlamaSlotAdapter, GPTSlotAdapter, adapter_for)
from .engine import InferenceEngine

__all__ = ["SlotKVCache", "Request", "Scheduler", "LlamaSlotAdapter",
           "GPTSlotAdapter", "adapter_for", "InferenceEngine"]
