"""Preallocated slot pool for serving K/V caches.

The whole cache is ONE pair of static-shaped device arrays,

    k, v : [n_slots, layers, kv_heads, max_len, head_dim]

allocated once at engine construction and never reshaped: every jitted
step sees the same shapes regardless of which requests occupy which
slots, so XLA compiles the slot-batched decode step exactly once (the
engine's compile-once guard asserts this).  A slot is the unit of
admission — one in-flight request owns one slot; retiring a request
returns its slot to the free list immediately, and the next queued
request reuses it mid-flight without touching the other slots.

Per-slot write positions (== current sequence length) are tracked
host-side in numpy and shipped into the step as a [n_slots] int32
operand; stale rows beyond a slot's position are never attended (the
step's mask is ``col <= position``) and are overwritten in order by
subsequent decode writes, so freeing/reusing a slot needs no cache
zeroing."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


class SlotKVCache:
    """Fixed pool of ``n_slots`` K/V cache slots on device."""

    def __init__(self, n_slots, layers, kv_heads, max_len, head_dim,
                 dtype=jnp.float32):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = int(n_slots)
        self.layers = int(layers)
        self.kv_heads = int(kv_heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        shape = (self.n_slots, self.layers, self.kv_heads, self.max_len,
                 self.head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # host mirrors: next write position (== tokens cached) per slot
        self.positions = np.zeros(self.n_slots, np.int32)
        self._free = list(range(self.n_slots - 1, -1, -1))  # pop() -> slot 0 first
        self._owner = [None] * self.n_slots
        self.alloc_count = 0
        self.free_count = 0
        # HBM accounting: the pool is the serving stack's dominant live
        # allocation — register it with the process-wide ledger
        from .. import telemetry
        self._hbm_handle = telemetry.get_hbm_ledger().alloc(
            "kv_cache", int(self.k.nbytes) + int(self.v.nbytes),
            owner=f"kv_cache:{id(self):x}")

    # -- allocation --------------------------------------------------------
    @property
    def n_free(self):
        return len(self._free)

    @property
    def n_active(self):
        return self.n_slots - len(self._free)

    def alloc(self, owner=None):
        """Claim a free slot (lowest id first); None when the pool is
        exhausted — admission control, not an error."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._owner[slot] = owner
        self.positions[slot] = 0
        self.alloc_count += 1
        return slot

    def free(self, slot):
        """Return ``slot`` to the pool.  Double-free is a bug in the
        scheduler and raises — a silently re-listed slot would be handed
        to two requests at once and corrupt both."""
        slot = int(slot)
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._free:
            raise RuntimeError(f"double free of slot {slot}")
        self._owner[slot] = None
        self.positions[slot] = 0
        self._free.append(slot)
        self.free_count += 1

    def owner(self, slot):
        return self._owner[slot]

    def allocated_slots(self):
        """Slots currently claimed (not on the free list), sorted."""
        free = set(self._free)
        return [s for s in range(self.n_slots) if s not in free]

    def audit(self):
        """Lifetime alloc/free accounting for the no-leak invariant the
        chaos bench asserts: after a drain, ``allocs == frees`` and
        ``in_use == 0`` — anything else means a slot leaked (lost to a
        crashed request) and the pool will eventually starve."""
        return {"allocs": self.alloc_count,
                "frees": self.free_count,
                "in_use": self.n_active}

    # -- step plumbing -----------------------------------------------------
    def device_positions(self):
        # SNAPSHOT, not view: on the CPU backend jnp.asarray may alias
        # the host buffer (or defer the copy), and ``advance``/``alloc``
        # mutate ``positions`` in place right after the decode dispatch
        # — uploading the live buffer raced the pending read and made
        # token streams nondeterministic (tier-1 serving flakes)
        return jnp.asarray(self.positions.copy())

    def advance(self, slots):
        """Bump the write position of ``slots`` after a decode step wrote
        one token each."""
        for s in slots:
            if self.positions[s] >= self.max_len:
                raise RuntimeError(
                    f"slot {s} overran max_len={self.max_len}")
            self.positions[s] += 1

    def update(self, k, v):
        """Adopt the cache arrays a jitted step returned."""
        self.k, self.v = k, v

    def close(self):
        """End the HBM-ledger accounting for this pool (idempotent).
        The arrays themselves are reclaimed by ordinary GC."""
        self._hbm_handle.free()
