"""Preallocated pools for serving K/V caches: dense slots and pages.

:class:`SlotKVCache` is the original dense pool — ONE pair of
static-shaped device arrays,

    k, v : [n_slots, layers, kv_heads, max_len, head_dim]

allocated once at engine construction and never reshaped: every jitted
step sees the same shapes regardless of which requests occupy which
slots, so XLA compiles the slot-batched decode step exactly once (the
engine's compile-once guard asserts this).  A slot is the unit of
admission — one in-flight request owns one slot; retiring a request
returns its slot to the free list immediately, and the next queued
request reuses it mid-flight without touching the other slots.

Per-slot write positions (== current sequence length) are tracked
host-side in numpy and shipped into the step as a [n_slots] int32
operand; stale rows beyond a slot's position are never attended (the
step's mask is ``col <= position``) and are overwritten in order by
subsequent decode writes, so freeing/reusing a slot needs no cache
zeroing.

:class:`PagedKVCache` keeps every one of those contracts but breaks the
``max_len``-per-slot HBM proportionality: the pool is

    k, v : [n_pages, layers, kv_heads, page_len, head_dim]

and a slot owns only the pages its reserved token span needs
(``ceil((prompt + max_new) / page_len)``, reserved in full at
admission so a request can never run out of pages mid-flight).  A
host-side ``[n_slots, max_pages]`` int32 block table maps a slot's
logical rows to pages; the jitted programs receive it as an operand and
gather ``pool[table]`` in-graph, so the executable — and therefore the
compile-once guarantee — is untouched by which pages a request happens
to hold.  Page 0 is a reserved sentinel: it is never allocated, every
unused block-table entry points at it, and the engine routes the
scatter-writes of inactive/padding lanes into it, so garbage rows land
in a page nothing ever reads unmasked.  Gathers of page 0 are harmless
for the same reason stale slot rows were: the attention mask is still
``col <= position``."""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import quant as _quant


def ceil_div(a, b):
    return -(-int(a) // int(b))


@jax.tree_util.register_pytree_node_class
class QuantizedKVPool:
    """A page pool stored as quantized codes plus per-row scales.

    ``codes [n_pages, L, KV, page_len, D]`` in the codec storage dtype
    (int8 / fp8 e4m3) and ``scales [n_pages, L, KV, page_len, 1]``
    float32 — one symmetric absmax scale per cached row's head_dim
    vector, the finest granularity :func:`scatter_rows` can maintain
    without cross-row reductions.  The class is a registered pytree so
    everything that moves pools (``jax.device_put``, mesh
    ``in_shardings``, donation) keeps working: the 5-D
    ``sharding.KV_POOL_SPEC`` applies to BOTH leaves unchanged because
    ``scales`` keeps the same leading four axes and only collapses the
    last one to a broadcast 1.

    The pool deliberately mimics the raw-array surface the engine and
    bench already consume — ``.shape`` (of the codes), ``.nbytes``
    (codes + scales: the scale overhead is real HBM and must be billed),
    and layer-range slicing (``pool[:, :n_layers]`` for the truncated
    self-draft) — so quantization stays a pool-construction decision,
    not an engine rewrite."""

    def __init__(self, codes, scales, qdtype):
        self.codes = codes
        self.scales = scales
        self.qdtype = str(qdtype)

    @classmethod
    def zeros(cls, shape, qdtype):
        codes = jnp.zeros(shape, _quant.code_dtype(qdtype))
        scales = jnp.zeros(tuple(shape[:-1]) + (1,), jnp.float32)
        return cls(codes, scales, qdtype)

    def tree_flatten(self):
        return (self.codes, self.scales), self.qdtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.codes.shape

    @property
    def dtype(self):
        """The LOGICAL element dtype (what dequantization yields) —
        shape/dtype introspection sites expect the math dtype, not the
        storage dtype."""
        return jnp.float32

    @property
    def nbytes(self):
        return int(self.codes.nbytes) + int(self.scales.nbytes)

    def __getitem__(self, idx):
        # layer-range slicing for the self-draft's truncated gather:
        # both leaves carry layers on axis 1, so one index applies to
        # each (anything fancier than basic slicing should go through
        # gather_pages, which dequantizes)
        return QuantizedKVPool(self.codes[idx], self.scales[idx],
                               self.qdtype)


@jax.jit
def _copy_page(pool, src, dst):
    """Device-side page copy for copy-on-write forks: duplicate page
    ``src`` into page ``dst`` without a host round-trip.  Quantized
    pools copy codes AND scales, so the fork starts bit-identical and
    every later write updates only its own scale rows — forked pages
    keep independent scales."""
    if isinstance(pool, QuantizedKVPool):
        return QuantizedKVPool(
            pool.codes.at[dst].set(pool.codes[src]),
            pool.scales.at[dst].set(pool.scales[src]),
            pool.qdtype)
    return pool.at[dst].set(pool[src])


def gather_pages(pool, block_tables):
    """Materialize per-slot contiguous caches from the page pool.

    ``pool [n_pages, L, KV, page_len, D]`` gathered by ``block_tables
    [S, max_pages]`` -> ``[S, L, KV, max_pages * page_len, D]`` with a
    slot's pages concatenated in logical order along the time axis —
    the exact layout the dense decode/prefill math already expects, so
    the model code is shared verbatim between the slot and paged paths.
    Quantized pools dequantize in-graph here (shared codec), so every
    consumer — decode, prefill, speculative verify — always attends
    float32 rows; the narrow dtype exists only at rest in the pool.
    """
    if isinstance(pool, QuantizedKVPool):
        g = _quant.dequantize_blocks(pool.codes[block_tables],
                                     pool.scales[block_tables])
    else:
        g = pool[block_tables]                  # [S, MP, L, KV, PL, D]
    s, mp, l, kv, pl, d = g.shape
    return jnp.transpose(g, (0, 2, 3, 1, 4, 5)).reshape(s, l, kv, mp * pl, d)


def scatter_rows(pool, pages, offsets, rows):
    """Write ``rows [N, L, KV, D]`` into ``pool`` at ``(pages[i],
    offsets[i])``.  Duplicate (page, offset) pairs only ever occur on
    the sentinel page 0 (inactive/padding lanes), where write order is
    irrelevant; live (page, offset) pairs are distinct by construction
    of the allocator.  Quantized pools quantize on write (shared
    codec): each row's head_dim vector gets its own absmax scale, and
    the codes/scales leaves are scattered with the same index pattern.

    Shared pages (refcount > 1) are read-only: a scatter into one would
    leak state between every request holding it.  The page indices here
    are tracers, so the invariant is enforced host-side — the engine
    computes the exact (slot, row-range) write set of every dispatch and
    runs it through :meth:`PagedKVCache.assert_writable` when the CoW
    write-guard is armed (``HETU_COW_GUARD=1``, on in the test suite),
    after :meth:`PagedKVCache.ensure_writable` has had its chance to
    fork divergent writers off shared pages."""
    if isinstance(pool, QuantizedKVPool):
        codes, scales = _quant.quantize_blocks(rows, dtype=pool.qdtype)
        return QuantizedKVPool(
            pool.codes.at[pages, :, :, offsets, :].set(codes),
            pool.scales.at[pages, :, :, offsets, :].set(scales),
            pool.qdtype)
    return pool.at[pages, :, :, offsets, :].set(rows)


class SlotKVCache:
    """Fixed pool of ``n_slots`` K/V cache slots on device."""

    def __init__(self, n_slots, layers, kv_heads, max_len, head_dim,
                 dtype=jnp.float32):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = int(n_slots)
        self.layers = int(layers)
        self.kv_heads = int(kv_heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        shape = (self.n_slots, self.layers, self.kv_heads, self.max_len,
                 self.head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # host mirrors: next write position (== tokens cached) per slot
        self.positions = np.zeros(self.n_slots, np.int32)
        self._free = list(range(self.n_slots - 1, -1, -1))  # pop() -> slot 0 first
        self._owner = [None] * self.n_slots
        self.alloc_count = 0
        self.free_count = 0
        # HBM accounting: the pool is the serving stack's dominant live
        # allocation — register it with the process-wide ledger
        from .. import telemetry
        self._hbm_handle = telemetry.get_hbm_ledger().alloc(
            "kv_cache", int(self.k.nbytes) + int(self.v.nbytes),
            owner=f"kv_cache:{id(self):x}")

    # -- allocation --------------------------------------------------------
    @property
    def n_free(self):
        return len(self._free)

    @property
    def n_active(self):
        return self.n_slots - len(self._free)

    def alloc(self, owner=None, n_tokens=None, shared=None):
        """Claim a free slot (lowest id first); None when the pool is
        exhausted — admission control, not an error.  ``n_tokens`` (the
        paged pool's worst-case reservation) is accepted and ignored:
        every dense slot already holds a full ``max_len`` span.
        ``shared`` (page-granular prefix sharing) is a paged-pool
        concept and must stay empty here."""
        del n_tokens
        if shared:
            raise ValueError(
                "SlotKVCache has no pages to share; prefix caching "
                "requires the paged pool")
        if not self._free:
            return None
        slot = self._free.pop()
        self._owner[slot] = owner
        self.positions[slot] = 0
        self.alloc_count += 1
        return slot

    def free(self, slot):
        """Return ``slot`` to the pool.  Double-free is a bug in the
        scheduler and raises — a silently re-listed slot would be handed
        to two requests at once and corrupt both."""
        slot = int(slot)
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._free:
            raise RuntimeError(f"double free of slot {slot}")
        self._owner[slot] = None
        self.positions[slot] = 0
        self._free.append(slot)
        self.free_count += 1

    def owner(self, slot):
        return self._owner[slot]

    def allocated_slots(self):
        """Slots currently claimed (not on the free list), sorted."""
        free = set(self._free)
        return [s for s in range(self.n_slots) if s not in free]

    def audit(self):
        """Lifetime alloc/free accounting for the no-leak invariant the
        chaos bench asserts: after a drain, ``allocs == frees`` and
        ``in_use == 0`` — anything else means a slot leaked (lost to a
        crashed request) and the pool will eventually starve."""
        return {"allocs": self.alloc_count,
                "frees": self.free_count,
                "in_use": self.n_active}

    # -- step plumbing -----------------------------------------------------
    def device_positions(self):
        # SNAPSHOT, not view: on the CPU backend jnp.asarray may alias
        # the host buffer (or defer the copy), and ``advance``/``alloc``
        # mutate ``positions`` in place right after the decode dispatch
        # — uploading the live buffer raced the pending read and made
        # token streams nondeterministic (tier-1 serving flakes)
        return jnp.asarray(self.positions.copy())

    def advance(self, slots):
        """Bump the write position of ``slots`` after a decode step wrote
        one token each."""
        for s in slots:
            if self.positions[s] >= self.max_len:
                raise RuntimeError(
                    f"slot {s} overran max_len={self.max_len}")
            self.positions[s] += 1

    def update(self, k, v):
        """Adopt the cache arrays a jitted step returned."""
        self.k, self.v = k, v

    def close(self):
        """End the HBM-ledger accounting for this pool (idempotent).
        The arrays themselves are reclaimed by ordinary GC."""
        self._hbm_handle.free()


class PagedKVCache:
    """Fixed page pool + per-slot block tables (see module doc).

    ``n_slots`` bounds concurrent requests (block-table operand rows),
    ``max_len`` bounds one request's total span (prompt + generated),
    ``page_len`` is the allocation granule, and ``n_pages`` sizes the
    pool — the HBM budget — independently of ``n_slots * max_len``;
    that decoupling is the whole point.  Default ``n_pages`` matches
    the dense pool's worst case (every slot at full ``max_len``) plus
    the sentinel, i.e. strictly safe; servers size it down to their
    real mix.  ``label`` names this pool in metrics and in flight-
    recorder incident dumps.

    ``kv_dtype`` (None | 'int8' | 'fp8') selects quantized page
    storage: the pools become :class:`QuantizedKVPool` pairs (codes +
    per-row scales), ``gather_pages`` dequantizes in-graph and
    ``scatter_rows`` quantizes on write, and every byte figure this
    class reports (HBM ledger, ``nbytes``) already includes the scale
    overhead.  ``None`` (default) is the existing float32 path,
    bitwise-untouched — quantization is strictly opt-in."""

    def __init__(self, n_slots, layers, kv_heads, page_len, head_dim,
                 max_len=128, n_pages=None, dtype=jnp.float32,
                 label=None, shards=1, put_sharding=None,
                 cow_guard=None, kv_dtype=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if page_len < 1:
            raise ValueError(f"page_len must be >= 1, got {page_len}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        self.n_slots = int(n_slots)
        self.layers = int(layers)
        self.kv_heads = int(kv_heads)
        self.page_len = int(page_len)
        self.head_dim = int(head_dim)
        self.max_len = int(max_len)
        self.max_pages = ceil_div(self.max_len, self.page_len)
        if n_pages is None:
            n_pages = self.n_slots * self.max_pages + 1  # + sentinel
        self.n_pages = int(n_pages)
        if self.n_pages < 2:
            raise ValueError(
                f"n_pages must be >= 2 (sentinel + one usable page), "
                f"got {self.n_pages}")
        self.label = str(label) if label is not None else f"kv:{id(self):x}"
        # tensor-parallel serving: ``shards`` is the model-axis degree
        # the pool is split over (kv_heads / shards live per chip), so
        # the HBM ledger records PER-CHIP bytes — the number headroom
        # gating compares against one device's capacity.
        # ``put_sharding`` places host-side step operands (positions,
        # block tables) once, replicated over the mesh, instead of
        # letting every jit dispatch reshard a single-device upload.
        self.shards = max(1, int(shards))
        self.put_sharding = put_sharding
        shape = (self.n_pages, self.layers, self.kv_heads, self.page_len,
                 self.head_dim)
        self.kv_dtype = None if kv_dtype is None else str(kv_dtype)
        if self.kv_dtype is None:
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
        else:
            # raises up front on an unknown/unsupported codec (fp8 on a
            # jax build without float8_e4m3fn) instead of at first write
            self.k = QuantizedKVPool.zeros(shape, self.kv_dtype)
            self.v = QuantizedKVPool.zeros(shape, self.kv_dtype)
        # host mirrors: write position + reserved token capacity per
        # slot, and the block tables the jitted programs consume.
        # Unused table entries stay 0 = the sentinel page.
        self.positions = np.zeros(self.n_slots, np.int32)
        self.capacity = np.zeros(self.n_slots, np.int32)
        self.block_tables = np.zeros((self.n_slots, self.max_pages),
                                     np.int32)
        self._free_slots = list(range(self.n_slots - 1, -1, -1))
        self._owner = [None] * self.n_slots
        self._slot_pages = [[] for _ in range(self.n_slots)]
        # page 0 is the sentinel: never on the free list.  pop() hands
        # out page 1 first.
        self._free_pages = list(range(self.n_pages - 1, 0, -1))
        # per-page refcounts: 1 for a privately-held page; >1 once a
        # prefix-cache shares it (copy-on-write groundwork — freeing a
        # slot only releases pages whose count hits 0)
        self._ref = np.zeros(self.n_pages, np.int32)
        # cached device copy of block_tables, dropped by every table
        # mutation (_take_page/free/share_pages): tables change only at
        # page-allocation events, but decode consumes them EVERY step —
        # re-uploading an unchanged [n_slots, max_pages] array per step
        # costs more host->device dispatch than the whole compiled step
        self._dev_tables = None
        self.alloc_count = 0
        self.free_count = 0
        self.page_alloc_count = 0
        self.page_free_count = 0
        self.cow_fork_count = 0
        # CoW write-guard: when armed, the engine routes every
        # dispatch's write set through assert_writable so a scatter
        # aimed at a shared page fails loudly instead of silently
        # corrupting every request holding it.  Debug mode — on in the
        # test suite (HETU_COW_GUARD=1), off by default in production.
        if cow_guard is None:
            cow_guard = os.environ.get("HETU_COW_GUARD", "") not in ("", "0")
        self.cow_guard = bool(cow_guard)
        # optional page reclaimer (a PrefixCache eviction hook): called
        # by alloc() when pages run short with the shortfall, returns
        # the number of pages it released — interned-but-idle prefixes
        # yield to live admissions before admission refuses.
        self.reclaim = None
        from .. import telemetry
        self._hbm_handle = telemetry.get_hbm_ledger().alloc(
            "kv_cache",
            (int(self.k.nbytes) + int(self.v.nbytes)) // self.shards,
            owner=f"kv_cache:{self.label}")
        reg = telemetry.get_registry()
        self._g_active = reg.gauge(
            "hetu_serving_pages_active",
            "KV pages currently allocated to slots, by pool",
            labels=("pool",))
        self._g_free = reg.gauge(
            "hetu_serving_pages_free",
            "KV pages on the free list (sentinel excluded), by pool",
            labels=("pool",))
        self._c_churn = reg.counter(
            "hetu_serving_page_churn_total",
            "KV page allocations + releases, by pool — allocation "
            "traffic, the page-level analogue of slot alloc/free",
            labels=("pool",))
        self._c_cow = reg.counter(
            "hetu_serving_prefix_cow_forks_total",
            "Copy-on-write page forks, by pool: a slot diverged inside "
            "a shared prefix page and was given a private copy",
            labels=("pool",))
        if self.kv_dtype is not None:
            # the scale arrays are the price of quantized pages: report
            # both sides so kv_hbm_bytes_per_token can be decomposed
            # (codes shrink 4x, scales add head_dim-fraction overhead)
            codes_b = int(self.k.codes.nbytes) + int(self.v.codes.nbytes)
            scales_b = (int(self.k.scales.nbytes)
                        + int(self.v.scales.nbytes))
            reg.gauge(
                "hetu_quant_kv_codes_bytes",
                "Quantized KV page-pool code bytes (both pools), by "
                "pool label", labels=("pool",)).labels(
                pool=self.label).set(codes_b // self.shards)
            reg.gauge(
                "hetu_quant_kv_scales_bytes",
                "Quantized KV page-pool scale bytes (the per-row "
                "float32 absmax scales — quantization's HBM overhead), "
                "by pool label", labels=("pool",)).labels(
                pool=self.label).set(scales_b // self.shards)
        self._flight = telemetry.get_flight()
        self._flight.register_pages(self.label, self.occupancy)
        self._sync_gauges()

    # -- allocation --------------------------------------------------------
    @property
    def n_free(self):
        return len(self._free_slots)

    @property
    def n_active(self):
        return self.n_slots - len(self._free_slots)

    @property
    def pages_active(self):
        return (self.n_pages - 1) - len(self._free_pages)

    @property
    def pages_free(self):
        return len(self._free_pages)

    @property
    def pages_shared(self):
        """Pages currently held by more than one owner (refcount > 1) —
        prefix-cache sharing in flight.  Zero means every live page is
        private and the CoW machinery is fully idle."""
        return int((self._ref > 1).sum())

    def _sync_gauges(self):
        self._g_active.labels(pool=self.label).set(self.pages_active)
        self._g_free.labels(pool=self.label).set(self.pages_free)

    def _take_page(self, slot):
        page = self._free_pages.pop()
        self._ref[page] = 1
        self._slot_pages[slot].append(page)
        self.block_tables[slot, len(self._slot_pages[slot]) - 1] = page
        self._dev_tables = None
        self.page_alloc_count += 1
        self._c_churn.labels(pool=self.label).inc()
        return page

    def alloc(self, owner=None, n_tokens=None, shared=None):
        """Claim a free slot AND reserve every page its span needs.

        ``n_tokens`` is the request's worst-case token span
        (prompt + max_new, plus any speculative lookahead); reserving
        ``ceil(n_tokens / page_len)`` pages up front means admission is
        the only place a request can be refused — no mid-flight page
        exhaustion, no preemption.  Returns None (admission control,
        not an error) when either slots or pages are short.

        ``shared`` is an optional sequence of already-filled page ids
        (a prefix-cache hit): they are mapped into the front of the
        slot's table with their refcount bumped — read-only until a
        copy-on-write fork — and count toward the reservation, so a
        prefix hit makes admission CHEAPER, never changes its shape."""
        n_tokens = self.max_len if n_tokens is None else int(n_tokens)
        if n_tokens < 1 or n_tokens > self.max_len:
            raise ValueError(
                f"n_tokens must be in [1, max_len={self.max_len}], "
                f"got {n_tokens}")
        need = ceil_div(n_tokens, self.page_len)
        shared = list(shared) if shared else []
        if len(shared) > need:
            raise ValueError(
                f"{len(shared)} shared pages exceed the {need}-page "
                f"reservation for n_tokens={n_tokens}")
        need_private = need - len(shared)
        if not self._free_slots:
            return None
        # pin the shared pages FIRST: the reclaim hook below may evict
        # the very prefix-cache entry whose pages this hit is about to
        # map, and the extra reference keeps them alive through it
        shared = [int(p) for p in shared]
        for page in shared:
            if self._ref[page] < 1:
                raise RuntimeError(
                    f"shared page {page} has refcount 0 (evicted "
                    f"between lookup and alloc?)")
            self._ref[page] += 1
        while need_private > len(self._free_pages):
            short = need_private - len(self._free_pages)
            if self.reclaim is None or not self.reclaim(short):
                self.release_pages(shared)   # unpin the refused hit
                return None
        slot = self._free_slots.pop()
        self._owner[slot] = owner
        self.positions[slot] = 0
        self.capacity[slot] = need * self.page_len
        for i, page in enumerate(shared):
            self._slot_pages[slot].append(page)
            self.block_tables[slot, i] = page
        if shared:
            self._dev_tables = None
        for _ in range(need_private):
            self._take_page(slot)
        self.alloc_count += 1
        self._sync_gauges()
        return slot

    def free(self, slot):
        """Return ``slot`` and its pages to the pool.  Double-free is a
        bug in the scheduler and raises; a shared page (refcount > 1)
        survives until its last holder releases it."""
        slot = int(slot)
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._free_slots:
            raise RuntimeError(f"double free of slot {slot}")
        for page in self._slot_pages[slot]:
            if self._ref[page] < 1:
                raise RuntimeError(
                    f"page {page} refcount underflow (double release)")
            self._ref[page] -= 1
            if self._ref[page] == 0:
                self._free_pages.append(page)
                self.page_free_count += 1
                self._c_churn.labels(pool=self.label).inc()
        self._slot_pages[slot] = []
        self.block_tables[slot, :] = 0
        self._dev_tables = None
        self._owner[slot] = None
        self.positions[slot] = 0
        self.capacity[slot] = 0
        self._free_slots.append(slot)
        self.free_count += 1
        self._sync_gauges()
        return None

    def share_pages(self, src, dst, n_pages):
        """Map ``src``'s first ``n_pages`` pages into ``dst``'s table
        (refcounted, read-only by convention) — the copy-on-write hook
        a prefix cache builds on.  ``dst`` must hold no pages yet."""
        src, dst, n_pages = int(src), int(dst), int(n_pages)
        if self._slot_pages[dst]:
            raise RuntimeError(
                f"slot {dst} already holds pages; share before append")
        if n_pages > len(self._slot_pages[src]):
            raise ValueError(
                f"slot {src} holds {len(self._slot_pages[src])} pages, "
                f"cannot share {n_pages}")
        for i in range(n_pages):
            page = self._slot_pages[src][i]
            self._ref[page] += 1
            self._slot_pages[dst].append(page)
            self.block_tables[dst, i] = page
        self._dev_tables = None
        self.capacity[dst] = n_pages * self.page_len
        self._sync_gauges()

    def slot_pages(self, slot):
        """The pages ``slot`` currently maps, in logical order."""
        return tuple(self._slot_pages[int(slot)])

    def retain_pages(self, pages):
        """Bump the refcount of ``pages`` on behalf of a slot-less
        owner (the prefix cache interning a finished prompt's prefix):
        the pages survive the writing slot's retirement and stay mapped
        until :meth:`release_pages`."""
        for page in pages:
            page = int(page)
            if self._ref[page] < 1:
                raise RuntimeError(
                    f"cannot retain page {page}: refcount is 0")
            self._ref[page] += 1

    def release_pages(self, pages):
        """Drop one reference from each of ``pages`` (prefix-cache
        eviction); pages whose count hits 0 return to the free list."""
        freed = 0
        for page in pages:
            page = int(page)
            if self._ref[page] < 1:
                raise RuntimeError(
                    f"page {page} refcount underflow (double release)")
            self._ref[page] -= 1
            if self._ref[page] == 0:
                self._free_pages.append(page)
                self.page_free_count += 1
                self._c_churn.labels(pool=self.label).inc()
                freed += 1
        self._sync_gauges()
        return freed

    def fork_page(self, slot, page_index):
        """Copy-on-write: give ``slot`` a private copy of the shared
        page at logical index ``page_index`` (device-side page copy +
        block-table rewrite).  No-op when the page is already private.
        Raises when the free list is empty — the engine's admission
        reservation makes that unreachable for the page-aligned prefix
        flow (writes start past the shared span); direct partial-page
        ``share_pages`` users own the headroom."""
        slot, page_index = int(slot), int(page_index)
        page = self._slot_pages[slot][page_index]
        if self._ref[page] <= 1:
            return page
        if not self._free_pages:
            raise RuntimeError(
                f"no free page for copy-on-write fork of page {page} "
                f"(slot {slot}); reserve fork headroom when sharing "
                f"partial pages")
        new = self._free_pages.pop()
        self._ref[new] = 1
        self.page_alloc_count += 1
        self._c_churn.labels(pool=self.label).inc()
        self.k = _copy_page(self.k, page, new)
        self.v = _copy_page(self.v, page, new)
        self._ref[page] -= 1          # was > 1, never hits 0 here
        self._slot_pages[slot][page_index] = new
        self.block_tables[slot, page_index] = new
        self._dev_tables = None
        self.cow_fork_count += 1
        self._c_cow.labels(pool=self.label).inc()
        self._sync_gauges()
        return new

    def _write_page_indices(self, slot, row0, n_rows):
        slot, row0, n_rows = int(slot), int(row0), int(n_rows)
        if n_rows < 1:
            return slot, range(0)
        held = len(self._slot_pages[slot])
        p0 = max(0, row0 // self.page_len)
        p1 = min(held - 1, (row0 + n_rows - 1) // self.page_len)
        return slot, range(p0, p1 + 1)

    def ensure_writable(self, slot, row0, n_rows=1):
        """Fork every shared page that rows ``[row0, row0 + n_rows)``
        of ``slot`` would touch — the first divergent write after a
        prefix share lands in a private copy.  Returns the number of
        forks performed (0 on the fast path: nothing shared)."""
        if self.pages_shared == 0:
            return 0
        forks = 0
        slot, prange = self._write_page_indices(slot, row0, n_rows)
        for pi in prange:
            if self._ref[self._slot_pages[slot][pi]] > 1:
                self.fork_page(slot, pi)
                forks += 1
        return forks

    def assert_writable(self, slot, row0, n_rows=1):
        """CoW write-guard (armed via ``HETU_COW_GUARD=1``, on in the
        test suite): raise if rows ``[row0, row0 + n_rows)`` of
        ``slot`` map any page with refcount > 1.  The jitted programs'
        page indices are tracers, so the read-only contract on shared
        pages is enforced here, at the host-side dispatch boundary,
        with the exact write set each dispatch is about to scatter."""
        slot, prange = self._write_page_indices(slot, row0, n_rows)
        for pi in prange:
            page = self._slot_pages[slot][pi]
            if self._ref[page] > 1:
                raise AssertionError(
                    f"write to SHARED page {page} (refcount="
                    f"{int(self._ref[page])}) by slot {slot}: rows "
                    f"[{int(row0)}, {int(row0) + int(n_rows)}) overlap "
                    f"logical page {pi}; fork before writing "
                    f"(ensure_writable) or share fewer pages")

    def owner(self, slot):
        return self._owner[slot]

    def allocated_slots(self):
        """Slots currently claimed (not on the free list), sorted."""
        free = set(self._free_slots)
        return [s for s in range(self.n_slots) if s not in free]

    # -- page migration (serving/kv_transfer.py rides these) ---------------
    def page_geometry(self):
        """The shape contract two pools must share for a page to move
        between them bit-identically: raw rows only splice into a pool
        with the same per-page layout AND the same at-rest encoding."""
        return {"layers": self.layers, "kv_heads": self.kv_heads,
                "page_len": self.page_len, "head_dim": self.head_dim,
                "kv_dtype": self.kv_dtype}

    def export_pages(self, pages):
        """Host-side snapshot of ``pages`` as RAW pool rows — float32
        arrays for a dense pool, codes + scales (never dequantized,
        never re-cast) for a quantized one — so an import on a
        matching pool reproduces the rows BITWISE.  The pages' live
        state (refcounts, tables) is untouched: export is a pure read,
        the donor keeps serving until the receiver acks."""
        pages = [int(p) for p in pages]
        if not pages:
            raise ValueError("export_pages needs at least one page")
        for p in pages:
            if not 1 <= p < self.n_pages:
                raise ValueError(
                    f"page {p} out of range (sentinel 0 excluded)")
            if self._ref[p] < 1:
                raise RuntimeError(
                    f"cannot export page {p}: refcount is 0 (freed)")
        idx = np.asarray(pages, np.int32)
        if self.kv_dtype is None:
            return {"kv_dtype": None,
                    "k": np.asarray(self.k[idx]),
                    "v": np.asarray(self.v[idx])}
        return {"kv_dtype": self.kv_dtype,
                "k_codes": np.asarray(self.k.codes[idx]),
                "k_scales": np.asarray(self.k.scales[idx]),
                "v_codes": np.asarray(self.v.codes[idx]),
                "v_scales": np.asarray(self.v.scales[idx])}

    def import_pages(self, payload):
        """Splice an :meth:`export_pages` payload into THIS pool:
        allocate fresh pages (through the same free-list accounting as
        ``alloc``, so ``audit`` stays balanced) and write the raw rows
        device-side.  Returns the new page ids — each with refcount 1
        OWNED BY THE CALLER, exactly like prefix-cache retained pages:
        map them into a slot via ``alloc(shared=...)`` and then
        ``release_pages`` the caller's reference, or ``release_pages``
        outright to abort.  Returns None when the pool is short of
        pages even after the reclaim hook (admission control, not an
        error); raises on a geometry/encoding mismatch — a payload
        from an incompatible pool can never splice bit-identically."""
        if payload.get("kv_dtype") != self.kv_dtype:
            raise ValueError(
                f"pool kv_dtype mismatch: payload "
                f"{payload.get('kv_dtype')!r} vs pool {self.kv_dtype!r}")
        lead = payload["k" if self.kv_dtype is None else "k_codes"]
        row_shape = (self.layers, self.kv_heads, self.page_len,
                     self.head_dim)
        for name, arr in payload.items():
            if name == "kv_dtype":
                continue
            want = (row_shape if not name.endswith("scales")
                    else row_shape[:-1] + (1,))
            if tuple(arr.shape[1:]) != want or arr.shape[0] != lead.shape[0]:
                raise ValueError(
                    f"payload array {name!r} shape {tuple(arr.shape)} "
                    f"does not match pool geometry {want}")
        n = int(lead.shape[0])
        if n < 1:
            raise ValueError("import_pages needs at least one page")
        while n > len(self._free_pages):
            short = n - len(self._free_pages)
            if self.reclaim is None or not self.reclaim(short):
                return None
        new = [self._free_pages.pop() for _ in range(n)]
        for p in new:
            self._ref[p] = 1
        self.page_alloc_count += n
        self._c_churn.labels(pool=self.label).inc(n)
        idx = jnp.asarray(np.asarray(new, np.int32))
        if self.kv_dtype is None:
            self.k = self.k.at[idx].set(
                jnp.asarray(payload["k"], self.k.dtype))
            self.v = self.v.at[idx].set(
                jnp.asarray(payload["v"], self.v.dtype))
        else:
            # raw codes + scales move as-is: requantizing would round
            # twice and break the bitwise-continuation contract
            self.k = QuantizedKVPool(
                self.k.codes.at[idx].set(jnp.asarray(payload["k_codes"])),
                self.k.scales.at[idx].set(
                    jnp.asarray(payload["k_scales"])),
                self.kv_dtype)
            self.v = QuantizedKVPool(
                self.v.codes.at[idx].set(jnp.asarray(payload["v_codes"])),
                self.v.scales.at[idx].set(
                    jnp.asarray(payload["v_scales"])),
                self.kv_dtype)
        self._sync_gauges()
        return new

    def audit(self):
        """Lifetime accounting for the no-leak invariants: after a
        drain ``allocs == frees``, ``in_use == 0``, AND ``page_allocs
        == page_frees`` with ``pages_in_use == 0`` — a leaked page
        starves admission just as surely as a leaked slot."""
        return {"allocs": self.alloc_count,
                "frees": self.free_count,
                "in_use": self.n_active,
                "page_allocs": self.page_alloc_count,
                "page_frees": self.page_free_count,
                "pages_in_use": self.pages_active}

    def occupancy(self):
        """Live page-pool occupancy/fragmentation — the block every
        flight-recorder incident dump carries (registered at
        construction) and the bench reports.  ``internal_fragmentation``
        is the fraction of reserved token capacity not yet written:
        worst-case reservation trades exactly this much slack for the
        no-preemption guarantee."""
        used = int(self.positions.sum())
        reserved = int(self.capacity.sum())
        usable = self.n_pages - 1
        return {"n_pages": self.n_pages,
                "page_len": self.page_len,
                "pages_active": self.pages_active,
                "pages_free": self.pages_free,
                "utilization": (round(self.pages_active / usable, 4)
                                if usable else 0.0),
                "internal_fragmentation": (round(1.0 - used / reserved, 4)
                                           if reserved else 0.0),
                "pages_shared": self.pages_shared,
                "cow_forks": self.cow_fork_count,
                "page_churn": self.page_alloc_count + self.page_free_count}

    # -- step plumbing -----------------------------------------------------
    def _put(self, host_array):
        if self.put_sharding is not None:
            return jax.device_put(host_array, self.put_sharding)
        return jnp.asarray(host_array)

    def device_positions(self):
        # SNAPSHOT, not view — same aliasing hazard as SlotKVCache
        return self._put(self.positions.copy())

    def device_block_tables(self):
        # SNAPSHOT, not view — ``free``/``alloc``/``share_pages``
        # rewrite table rows in place between decode dispatches.  The
        # snapshot is CACHED between mutations (every writer drops
        # ``_dev_tables``): block tables change only at page-allocation
        # events, so steady-state decode reuses one device buffer
        # instead of paying an upload dispatch per step.
        if self._dev_tables is None:
            self._dev_tables = self._put(self.block_tables.copy())
        return self._dev_tables

    def advance(self, slots):
        """Bump the write position of ``slots`` after a decode step
        wrote one token each.  The guard is per-slot reserved capacity,
        not the global ``max_len`` — overrunning a reservation would
        scatter into another request's page."""
        for s in slots:
            if self.positions[s] >= self.capacity[s]:
                raise RuntimeError(
                    f"slot {s} overran its reserved capacity="
                    f"{int(self.capacity[s])} (page_len={self.page_len})")
            self.positions[s] += 1

    def advance_by(self, slot, n):
        """Bump ``slot``'s write position by ``n`` rows at once — the
        speculative verify step commits 1..k+1 accepted tokens per
        iteration.  Rows written beyond the committed span (rejected
        speculative tokens) are simply never advanced over: the
        ``col <= position`` mask keeps them unattendable and the next
        write at those positions overwrites them — host-side block-table
        state IS the rollback, no device work needed."""
        slot, n = int(slot), int(n)
        if n < 0:
            raise ValueError(f"advance_by needs n >= 0, got {n}")
        if self.positions[slot] + n > self.capacity[slot]:
            raise RuntimeError(
                f"slot {slot} would overrun its reserved capacity="
                f"{int(self.capacity[slot])} (position="
                f"{int(self.positions[slot])}, advance {n})")
        self.positions[slot] += n

    def update(self, k, v):
        """Adopt the cache arrays a jitted step returned."""
        self.k, self.v = k, v

    def close(self):
        """End HBM-ledger accounting and unhook the flight-recorder
        occupancy provider (idempotent)."""
        self._hbm_handle.free()
        self._flight.unregister_pages(self.label)
