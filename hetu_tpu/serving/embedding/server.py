"""EmbeddingServer: batched CTR scoring behind the serving lifecycle.

One embedding request = one example's sparse feature ids (``[F]``
int32, stored where an LLM request stores its prompt) plus optional
dense features; it completes in a SINGLE scheduler iteration — admit,
one batched tier lookup, one jitted score step, retire with
``finish_reason="scored"``.  That makes embedding traffic the
microsecond-scale stress test of the serving lifecycle: the server
reuses the REAL :class:`~..scheduler.Scheduler` (not a clone), so
bounded-queue admission (typed ``EngineOverloaded``), TTL/deadlines at
admission and mid-flight, ``cancel()``, shed policies, rid scoping, and
the queue-depth telemetry all behave exactly as they do for LLM
requests — and ``EngineFleet(engine_factory=EmbeddingServer)`` routes,
health-checks, and fails embedding traffic over unchanged (a harvested
embedding request re-homes with an empty replay: nothing was delivered,
the sibling just scores it).

The scoring program is the engine pattern re-hosted: exactly ONE jitted
program per (model, shape) signature, shared process-wide
(compile-once; ``trace_counts`` is the witness), computing an in-graph
per-slot finiteness sentinel so the watchdog is a host-side decision
over the same executable.  Cached mode gathers rows from the
:class:`~.hot_cache.DeviceHotRowCache` via the ``packed_lookup`` pallas
path (ids are cache slots); ``cache_rows=None`` builds the UNCACHED
host-tier twin — every batch gathers its rows on the host and ships
them up, the DLRM-inference bottleneck the bench quantifies against.
"""

from __future__ import annotations

import time
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from ... import telemetry as _telemetry
from ...models.ctr import make_wdl_scorer
from ...ops.pallas.sparse_densify import packed_lookup
from ...ps.store import EmbeddingTable
from ..scheduler import Request, Scheduler
from .hot_cache import DeviceHotRowCache, EMBED_BUCKETS, as_host_tier


class BatchSlotPool:
    """Slot pool for batch seats (the SlotKVCache alloc/free surface
    without the K/V arrays): one in-flight embedding request owns one
    seat of the fixed ``[n_slots, F]`` scoring batch.  Reusing the
    exact surface lets the serving :class:`~..scheduler.Scheduler`
    drive admission unchanged."""

    def __init__(self, n_slots):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = int(n_slots)
        self._free = list(range(self.n_slots - 1, -1, -1))
        self._owner = [None] * self.n_slots
        self.alloc_count = 0
        self.free_count = 0

    @property
    def n_free(self):
        return len(self._free)

    @property
    def n_active(self):
        return self.n_slots - len(self._free)

    def alloc(self, owner=None, n_tokens=None):
        # ``n_tokens`` (worst-case token span) is a KV-pool concern the
        # scheduler passes uniformly; batch seats have no token axis
        del n_tokens
        if not self._free:
            return None
        slot = self._free.pop()
        self._owner[slot] = owner
        self.alloc_count += 1
        return slot

    def free(self, slot):
        slot = int(slot)
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._free:
            raise RuntimeError(f"double free of slot {slot}")
        self._owner[slot] = None
        self._free.append(slot)
        self.free_count += 1

    def owner(self, slot):
        return self._owner[slot]

    def allocated_slots(self):
        free = set(self._free)
        return [s for s in range(self.n_slots) if s not in free]

    def audit(self):
        return {"allocs": self.alloc_count, "frees": self.free_count,
                "in_use": self.n_active}


class EmbedRequest(Request):
    """One scoring request: ``prompt`` holds the sparse ids ``[F]``,
    ``dense`` the dense features, ``scores`` the result.  ``tokens``
    stays EMPTY — an embedding request either finishes inside one
    iteration or was never served, so a fleet failover always re-homes
    it with no replay."""

    def __init__(self, ids, dense=None, **kw):
        super().__init__(ids, kw.pop("max_new", 1), **kw)
        self.dense = dense
        self.scores = []

    def result(self):
        return np.asarray(self.scores, np.float32)


class EmbeddingServer:
    """Tiered embedding serving through the Scheduler lifecycle.

    ``host_table=`` is the cold tier (``ps.EmbeddingTable``,
    ``ps.CacheSparseTable``, or anything with ``lookup``/``versions``);
    by default the server SPILLS the model's in-graph table to a fresh
    host-RAM ``EmbeddingTable`` — serve-a-trained-model without keeping
    the table in device memory.  ``cache_rows=`` sizes the device
    hot-row tier (must hold at least one batch of unique ids,
    ``n_slots * num_sparse``); ``cache_rows=None`` disables it — the
    uncached host-tier twin the bench compares against.

    ``close()`` (or the context manager) tears the server down,
    shutting down a ``CacheSparseTable`` cold tier's worker thread with
    it unless ``own_host_table=False`` says the table is shared (a
    fleet of replicas over one table)."""

    def __init__(self, executor, model, host_table=None, cache_rows=None,
                 n_slots=8, policy="lfu", staleness_bound=0,
                 max_queue=None, low_watermark=None,
                 shed_policy="reject_newest", watchdog=True, clock=None,
                 instance=None, latency_buckets=None, device=None,
                 name=None, own_host_table=None, use_pallas=True):
        self.params = executor.params
        self.model = model
        self.instance = None if instance is None else str(instance)
        self.device = device
        if device is not None:
            self.params = jax.device_put(self.params, device)
        self.num_sparse = int(model.num_sparse)
        self.dim = int(model.embedding_dim)
        self.num_dense = int(
            np.asarray(self.params[model.wide.weight.name]).shape[0])
        self.n_slots = int(n_slots)
        self.name = str(name) if name is not None else (
            self.instance or "embed")
        self.watchdog = bool(watchdog)
        self._clock = clock if clock is not None else time.perf_counter
        self.use_pallas = bool(use_pallas)
        if host_table is None:
            # spill the trained in-graph table to host RAM: the device
            # never holds the full table again, exactly the
            # bigger-than-HBM serving shape the PS tier exists for
            rows = model.emb.host_table(self.params)
            table = EmbeddingTable(rows.shape[0], self.dim, lr=0.0,
                                   init_scale=0.0)
            table.set_rows(np.arange(rows.shape[0]), rows)
            host_table = table
            own_host_table = True if own_host_table is None \
                else own_host_table
        self._host_raw = host_table
        self.host = as_host_tier(host_table)
        self.own_host_table = (True if own_host_table is None
                               else bool(own_host_table))
        self._closed = False
        self.hot = None
        if cache_rows:
            if int(cache_rows) < self.n_slots * self.num_sparse:
                raise ValueError(
                    f"cache_rows={cache_rows} cannot hold one batch of "
                    f"unique ids (n_slots*num_sparse = "
                    f"{self.n_slots * self.num_sparse})")
            self.hot = DeviceHotRowCache(
                self.host, cache_rows, self.dim, policy=policy,
                staleness_bound=staleness_bound,
                name=f"{self.name}_hot", device=device)
        self.pool = BatchSlotPool(self.n_slots)
        self.cache = self.pool     # fleet-facing alias (engine.cache)
        self.scheduler = Scheduler(self.pool,
                                   prefill_budget=self.n_slots,
                                   max_queue=max_queue,
                                   low_watermark=low_watermark,
                                   shed_policy=shed_policy,
                                   rid_prefix=self.instance)
        self.records = []
        self.iterations = 0
        self.requests_scored = 0
        self.cancellations = 0
        self.expirations = 0
        self.watchdog_trips = 0
        self.streams_detached = 0
        self.lookup_seconds = []
        self.score_seconds = []
        reg = _telemetry.get_registry()
        hkw = {"buckets": (EMBED_BUCKETS if latency_buckets is None
                           else tuple(latency_buckets))}

        def _m(kind, mname, help, **kw):
            return getattr(reg, kind)(mname, help, labels=("server",),
                                      **kw).labels(server=self.name)

        self._m_scored = _m("counter", "hetu_embed_requests_total",
                            "Embedding requests retired (any "
                            "finish_reason)")
        self._m_rows = _m("counter", "hetu_embed_rows_served_total",
                          "Embedding rows gathered for scored requests")
        self._m_iters = _m("counter", "hetu_embed_iterations_total",
                           "Scoring iterations run")
        self._m_cancelled = _m("counter",
                               "hetu_embed_cancellations_total",
                               "Embedding requests cancelled")
        self._m_expired = _m("counter",
                             "hetu_embed_deadline_expired_total",
                             "Embedding requests expired past their TTL")
        self._m_watchdog = _m(
            "counter", "hetu_embed_watchdog_trips_total",
            "Scoring watchdog quarantines (non-finite score or a "
            "raising step)")
        self._m_lookup = reg.histogram(
            "hetu_embed_lookup_seconds",
            "Per-iteration tier lookup latency",
            labels=("server", "tier"), **hkw)
        self._m_score = _m("histogram", "hetu_embed_score_seconds",
                           "Per-iteration jitted scoring latency", **hkw)
        self._m_ttft = _m("histogram", "hetu_embed_ttft_seconds",
                          "Arrival -> score latency per request", **hkw)
        self._tr = _telemetry.get_tracer()
        self._rt = _telemetry.get_request_trace()
        self._fl = _telemetry.get_flight()
        self._build()

    # -- jitted scoring program --------------------------------------------
    # ONE compiled scorer per (model names, shapes, mode) signature in
    # the process, shared across server instances — same rationale as
    # InferenceEngine._PROGRAMS: twins/rebuilds/fleet replicas reuse the
    # executable, and the finiteness sentinel is in-graph for EVERY
    # server so protection stays a host-side decision.
    _PROGRAMS = {}

    def _program_key(self):
        mode = "cached" if self.hot is not None else "direct"
        shape = (self.n_slots, self.num_sparse, self.dim, self.num_dense,
                 None if self.hot is None else self.hot.padded_rows)
        return (type(self.model).__name__, self._names, shape, mode,
                self.use_pallas, jax.default_backend())

    def _build(self):
        score, self._names = make_wdl_scorer(self.model)
        entry = self._PROGRAMS.get(self._program_key())
        if entry is None:
            dim, use_pallas = self.dim, self.use_pallas
            p_rows = None if self.hot is None else self.hot.p_rows
            from ... import telemetry as _tel
            retrace = _tel.get_registry().counter(
                "hetu_embed_retraces_total",
                "Times each jitted scoring program was traced — >1 "
                "after warmup breaks the compile-once contract",
                labels=("program",))
            mode = "cached" if self.hot is not None else "direct"
            traces = {mode: 0}

            if self.hot is not None:
                def score_step(params, table_dev, slot_ids, dense,
                               active):
                    traces[mode] += 1   # host-side retrace witness
                    retrace.labels(program=mode).inc()
                    packed = table_dev.reshape(p_rows, 128)
                    rows = packed_lookup(packed, slot_ids, dim,
                                         use_pallas)
                    logits = score(params, rows, dense)
                    ok = jnp.isfinite(logits)
                    return jnp.where(active, logits, 0.0), ok
            else:
                def score_step(params, rows, dense, active):
                    traces[mode] += 1   # host-side retrace witness
                    retrace.labels(program=mode).inc()
                    logits = score(params, rows, dense)
                    ok = jnp.isfinite(logits)
                    return jnp.where(active, logits, 0.0), ok

            entry = {"fn": jax.jit(score_step), "traces": traces}
            self._PROGRAMS[self._program_key()] = entry
        self._score_fn = entry["fn"]
        self._traces = entry["traces"]

    @property
    def trace_counts(self):
        """Shared retrace counters (compile-once witness): 1 after
        warmup means every server with this signature runs one
        executable."""
        return dict(self._traces)

    # AOT scoring executables keyed by cost_signature(), mirroring the
    # engine: repeat raw cost_programs() calls stay retrace-flat
    _COST_PROGRAMS = {}

    def cost_signature(self):
        """Stable identity of the compiled scoring program at this
        server's serving shapes — the profiler's capture-cache key
        (same program key + slot/feature geometry means the same
        executable, so a cached cost capture is exact)."""
        return repr((self._program_key(), self.n_slots, self.num_dense,
                     self.num_sparse, self.dim))

    def cost_programs(self, force=False):
        """AOT-lower + compile the scoring program at this server's
        exact serving shapes; ``{"score": compiled}`` for the profiling
        layer.  Pure analysis; results are cached per
        :meth:`cost_signature`, so only the first call per signature
        re-traces the shared python callable (``force=True`` rebuilds
        unconditionally)."""
        sig = self.cost_signature()
        if not force:
            cached = self._COST_PROGRAMS.get(sig)
            if cached is not None:
                return dict(cached)

        def ab(x):
            return jax.ShapeDtypeStruct(jnp.shape(x), x.dtype)

        params = jax.tree_util.tree_map(ab, self.params)
        n = self.n_slots
        dense = jax.ShapeDtypeStruct((n, self.num_dense), jnp.float32)
        active = jax.ShapeDtypeStruct((n,), jnp.bool_)
        if self.hot is not None:
            gathered = (ab(self.hot.packed_view()),
                        jax.ShapeDtypeStruct((n, self.num_sparse),
                                             jnp.int32))
        else:
            gathered = (jax.ShapeDtypeStruct(
                (n, self.num_sparse, self.dim), jnp.float32),)
        progs = {"score": self._score_fn.lower(
            params, *gathered, dense, active).compile()}
        self._COST_PROGRAMS[sig] = dict(progs)
        return progs

    # -- request API --------------------------------------------------------
    def submit(self, ids, max_new=1, stream=None, eos_id=None,
               arrival=None, deadline=None, ttl=None, replay=None,
               rid=None, dense=None):
        """Queue one scoring request (ids ``[num_sparse]`` int); the
        engine-compatible signature lets ``EngineFleet`` dispatch and
        fail embedding traffic over unchanged.  ``stream(score, req)``
        fires once, when the score is produced.  Raises
        :class:`~..scheduler.EngineOverloaded` when the bounded queue
        refuses admission."""
        self._require_open()
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size != self.num_sparse:
            raise ValueError(
                f"expected {self.num_sparse} sparse ids per request, "
                f"got {ids.size}")
        if dense is None:
            dense = np.zeros(self.num_dense, np.float32)
        dense = np.asarray(dense, np.float32).reshape(-1)
        if dense.size != self.num_dense:
            raise ValueError(
                f"expected {self.num_dense} dense features, got "
                f"{dense.size}")
        now = self._now()
        if ttl is not None:
            if deadline is not None:
                raise ValueError("pass ttl= or deadline=, not both")
            if ttl <= 0:
                raise ValueError(f"ttl must be > 0, got {ttl}")
            deadline = now + float(ttl)
        req = EmbedRequest(ids, dense=dense,
                           arrival=now if arrival is None else arrival,
                           stream=stream, eos_id=eos_id,
                           deadline=deadline, replay=replay, rid=rid)
        try:
            self.scheduler.submit(req, now=now)
        finally:
            for shed in self.scheduler.drain_shed():
                self.expirations += 1
                self._m_expired.inc()
                self._finalize_unadmitted(shed, "deadline", now)
        return req

    def cancel(self, rid):
        """Cancel the live request with this rid (queued, or running if
        caught inside an iteration); finishes with
        ``finish_reason="cancelled"``."""
        req = self.scheduler.find(rid)
        if req is None:
            return False
        now = self._now()
        req.cancel_requested = True
        if req.slot is not None:
            self._finalize_active(req, "cancelled", now)
        else:
            self.scheduler.remove_queued(req)
            self._finalize_unadmitted(req, "cancelled", now)
        self.cancellations += 1
        self._m_cancelled.inc()
        return True

    def harvest(self):
        """Remove every live request for fleet failover (attempt-level
        ``finish_reason="failover"``); running before queued, the order
        a sibling re-admits them in.  Embedding attempts never delivered
        anything, so the fleet re-homes them with an empty replay."""
        now = self._now()
        out = []
        for req in list(self.scheduler.running.values()):
            self._finalize_active(req, "failover", now)
            out.append(req)
        while self.scheduler.queue:
            req = self.scheduler.queue.popleft()
            self._finalize_unadmitted(req, "failover", now)
            out.append(req)
        return out

    def _now(self):
        return self._clock()

    def _require_open(self):
        if self._closed:
            raise RuntimeError(f"EmbeddingServer {self.name} is closed")

    # -- bookkeeping --------------------------------------------------------
    def _record(self, req):
        self.records.append({
            "id": req.rid, "prompt_len": int(req.prompt.size),
            "n_tokens": len(req.scores),
            "queue_wait": req.queue_wait, "ttft": req.ttft,
            "tpot": req.tpot, "finish_reason": req.finish_reason})
        # same timeline vocabulary as the LLM engine (request_trace.py)
        reason = req.finish_reason
        if reason == "deadline":
            self._rt.event(req.rid, "expired", engine=self.instance)
        elif reason == "cancelled":
            self._rt.event(req.rid, "cancelled", engine=self.instance)
        elif reason == "failover":
            self._rt.event(req.rid, "harvested", engine=self.instance)
        self._rt.event(req.rid, "finish", engine=self.instance,
                       reason=reason, scores=len(req.scores))
        self._m_scored.inc()
        if req.ttft is not None:
            self._m_ttft.observe(req.ttft)

    def _finalize_active(self, req, reason, now):
        req.t_done = now
        self.scheduler.retire(req, reason)
        self._record(req)

    def _finalize_unadmitted(self, req, reason, now):
        req.t_done = now
        req.finished = True
        req.finish_reason = reason
        self._record(req)

    def _expire(self, now):
        for req in self.scheduler.take_expired(now):
            self.expirations += 1
            self._m_expired.inc()
            self._finalize_unadmitted(req, "deadline", now)

    def _trip(self, req, why, now):
        self.watchdog_trips += 1
        self._m_watchdog.inc()
        warnings.warn(
            f"embedding watchdog: {why} for request {req.rid} — "
            "quarantined (finish_reason='error')")
        self._rt.event(req.rid, "watchdog_trip", engine=self.instance,
                       why=why)
        self._fl.incident("watchdog", rid=req.rid,
                          extra={"engine": self.instance, "why": why})
        self._finalize_active(req, "error", now)

    def _emit(self, req, value, now):
        req.scores.append(float(value))
        if req.t_first is None:
            req.t_first = now
        if req.stream is not None:
            try:
                req.stream(float(value), req)
            except Exception as e:
                if not self.watchdog:
                    raise
                req.stream = None
                self.streams_detached += 1
                warnings.warn(
                    f"stream callback for request {req.rid} raised "
                    f"{type(e).__name__}: {e} — detached (score lands "
                    "in result())")

    # -- the iteration ------------------------------------------------------
    def step(self):
        """One scheduler iteration: expire, admit up to ``n_slots``
        requests, ONE batched tier lookup, ONE jitted score step, retire
        everything scored.  Returns the number of requests scored."""
        self._require_open()
        now = self._now()
        self._expire(now)
        for req, slot in self.scheduler.admit():
            req.t_admit = now
            self._rt.event(req.rid, "admitted", engine=self.instance,
                           slot=slot)
            if req.expired(now):
                # mid-flight expiry: admitted this very iteration but
                # already past deadline — partial terminal, seat freed
                self.expirations += 1
                self._m_expired.inc()
                self._finalize_active(req, "deadline", now)
        live = sorted(self.scheduler.running.items())
        if not live:
            return 0
        slots = [s for s, _ in live]
        reqs = [r for _, r in live]
        ids = np.stack([r.prompt for r in reqs])            # [A, F]
        dense = np.zeros((self.n_slots, self.num_dense), np.float32)
        dense[slots] = np.stack([r.dense for r in reqs])
        active = np.zeros(self.n_slots, bool)
        active[slots] = True
        tier = "device_hot" if self.hot is not None else "host_table"
        t0 = time.perf_counter()
        hot0 = ((self.hot.hits, self.hot.misses + self.hot.refreshes)
                if self.hot is not None else (0, 0))
        try:
            with self._tr.span("embed_lookup"):
                if self.hot is not None:
                    slot_ids = np.zeros((self.n_slots, self.num_sparse),
                                        np.int32)
                    slot_ids[slots] = self.hot.lookup_slots(ids)
                    gathered = (self.hot.packed_view(),
                                jnp.asarray(slot_ids))
                else:
                    # the uncached twin: the DLRM-paper host gather —
                    # every batch fetches its rows from host RAM and
                    # ships them up
                    rows = np.zeros(
                        (self.n_slots, self.num_sparse, self.dim),
                        np.float32)
                    rows[slots] = self.host.lookup(
                        ids.reshape(-1)).reshape(ids.shape + (self.dim,))
                    gathered = (jnp.asarray(rows),)
            dt = time.perf_counter() - t0
            self.lookup_seconds.append(dt)
            self._m_lookup.labels(server=self.name, tier=tier).observe(dt)
            # per-tier lookup events, batch-attributed: the tier gather
            # is ONE batched op, so every live request gets one event
            # naming where its iteration's rows came from (cache hits
            # vs host pulls for misses+stale; the uncached twin always
            # pulls from the host table)
            if self._rt.enabled:
                if self.hot is not None:
                    d_hits = self.hot.hits - hot0[0]
                    d_pulls = (self.hot.misses + self.hot.refreshes
                               - hot0[1])
                    for req in reqs:
                        if d_hits:
                            self._rt.event(req.rid, "hot_hit",
                                           engine=self.instance,
                                           tier=tier,
                                           batch_rows=d_hits)
                        if d_pulls:
                            self._rt.event(req.rid, "host_pull",
                                           engine=self.instance,
                                           tier=tier,
                                           batch_rows=d_pulls)
                else:
                    for req in reqs:
                        self._rt.event(req.rid, "host_pull",
                                       engine=self.instance, tier=tier,
                                       batch_rows=int(ids.size))
            t1 = time.perf_counter()
            with self._tr.span("embed_score"):
                scores, ok = self._score_fn(
                    self.params, *gathered, jnp.asarray(dense),
                    jnp.asarray(active))
                scores = np.asarray(scores)
                ok = np.asarray(ok)
            dt = time.perf_counter() - t1
            self.score_seconds.append(dt)
            self._m_score.observe(dt)
        except Exception as e:
            if not self.watchdog:
                raise
            now = self._now()
            for req in list(self.scheduler.running.values()):
                self._trip(req, f"scoring step raised "
                           f"{type(e).__name__}: {e}", now)
            return 0
        self.iterations += 1
        self._m_iters.inc()
        now = self._now()
        produced = 0
        for slot, req in zip(slots, reqs):
            if self.watchdog and not ok[slot]:
                self._trip(req, "non-finite score", now)
                continue
            self._emit(req, scores[slot], now)
            self.requests_scored += 1
            produced += 1
            self._m_rows.inc(self.num_sparse)
            self._finalize_active(req, "scored", now)
        return produced

    def run(self, max_iterations=None):
        """Step until queue and seats drain; returns iterations used."""
        it = 0
        while not self.scheduler.idle:
            if max_iterations is not None and it >= max_iterations:
                raise RuntimeError(
                    f"server did not drain in {max_iterations} "
                    "iterations")
            self.step()
            it += 1
        return it

    def score_many(self, ids_batch, dense_batch=None, max_iterations=None):
        """Synchronous batch API: submit all, drain, return the scores
        ``[n]`` float32 (NaN for any request that did not finish
        "scored")."""
        n = len(ids_batch)
        reqs = [self.submit(ids_batch[i],
                            dense=None if dense_batch is None
                            else dense_batch[i])
                for i in range(n)]
        self.run(max_iterations=max_iterations or 2 * n + 4)
        return np.asarray(
            [r.scores[0] if r.scores else np.nan for r in reqs],
            np.float32)

    # -- teardown -----------------------------------------------------------
    def close(self):
        """Tear the server down: refuse new work and shut down an OWNED
        cold tier (a ``CacheSparseTable``'s worker thread dies here —
        the teardown ownership the thread-leak gate's allowlist names).
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self.hot is not None:
            self.hot.close()   # ends its hot_cache HBM-ledger entry
        if self.own_host_table and hasattr(self._host_raw, "close"):
            self._host_raw.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- reporting ----------------------------------------------------------
    def reset_stats(self):
        """Clear per-request records and counters (NOT the shared trace
        counters — the compile-once guard still needs them)."""
        self.records = []
        self.iterations = 0
        self.requests_scored = 0
        self.cancellations = 0
        self.expirations = 0
        self.watchdog_trips = 0
        self.streams_detached = 0
        self.lookup_seconds = []
        self.score_seconds = []

    def stats(self):
        out = {"n_slots": self.n_slots,
               "iterations": self.iterations,
               "requests_finished": len(self.records),
               "requests_scored": self.requests_scored,
               "slot_allocs": self.pool.alloc_count,
               "slot_frees": self.pool.free_count,
               "rejections": self.scheduler.rejected,
               "queue_depth_peak": self.scheduler.queue_depth_peak,
               "cancellations": self.cancellations,
               "expirations": self.expirations,
               "watchdog_trips": self.watchdog_trips,
               "streams_detached": self.streams_detached,
               "trace_counts": self.trace_counts}
        if self.hot is not None:
            out["hot_cache"] = self.hot.stats()
        return out
