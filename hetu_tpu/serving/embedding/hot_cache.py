"""Device-resident hot-row embedding cache (HET-style, bounded stale).

"Dissecting Embedding Bag Performance in DLRM Inference" (PAPERS.md)
shows the host-side gather path dominating DLRM inference latency; the
fix here is the HET client-cache idea (PAPER.md, reference
src/hetu_cache) re-hosted on the accelerator: the hot rows of a
host-RAM embedding table live in ONE preallocated ``[cache_rows, dim]``
HBM array, and the per-batch lookup becomes a host-side id→cache-slot
translation (numpy dict/array work, microseconds) plus an on-device
packed gather inside the scoring program — no per-request host↔device
row traffic at all on a cache hit.

Contracts:

* **admission/eviction** — LFU (default) or LRU over cache slots; a
  batch's own rows are pinned and never evicted by that batch.  Rows
  enter and refresh through ONE batched scatter per lookup call
  (``rows_dev.at[slots].set(rows)``, donated off-CPU) — never per-row
  transfers.
* **staleness bound** — the host table versions every row (bumped per
  push/set_rows, ``ps/native``).  A cached row is served only while
  ``host_version - cached_version <= staleness_bound``; past the bound
  the lookup forces a refresh.  Bound 0 ⇒ every served row is bitwise
  identical to the host table at serve time (the HET pull-bound
  semantics, measured in row updates, not wall time).  Versions are
  read BEFORE rows on fetch, so a racing update can only make the
  cache refresh EARLIER than the bound requires, never later.
* **layout** — rows are stored ``[padded_rows, dim]`` where
  ``padded_rows = packed_rows(cache_rows, dim) * (128 // dim)``: a free
  device-side reshape to ``[p_rows, 128]`` is exactly the packed-table
  layout, so the scoring program gathers through ``packed_lookup``
  (the scatter-free pallas path) with cache SLOTS as the ids.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ... import telemetry as _telemetry
from ...ops.pallas.sparse_densify import pack_factor, packed_rows

#: default histogram ladder for the embedding path: serving latencies
#: here are MICROsecond-scale (host dict work + one device gather), so
#: the serving DEFAULT_BUCKETS' 100us floor would fold every sample
#: into its first bucket.  Override per deployment with the
#: ``latency_buckets=`` threading (PR 6) on EmbeddingServer.
EMBED_BUCKETS = (1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4,
                 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 0.1, 1.0)

POLICIES = ("lfu", "lru")


def as_host_tier(obj):
    """Adapt a cold-tier object to the ``lookup(keys)`` /
    ``versions(keys)`` surface the cache needs.

    Accepts a ``ps.EmbeddingTable`` (has both), a ``ps.CacheSparseTable``
    (lookups go through its HET host cache — synchronously, the cache
    owns the ordering; versions come from the authoritative table), or
    anything already exposing both methods.  Note the staleness bounds
    COMPOSE: a CacheSparseTable cold tier adds its own ``pull_bound``
    on top of the device cache's ``staleness_bound`` (use
    ``pull_bound=0`` when the device bound must be exact)."""
    if hasattr(obj, "lookup") and hasattr(obj, "versions"):
        return obj

    class _CSTTier:
        def __init__(self, cst):
            self._cst = cst

        def lookup(self, keys):
            return self._cst.embedding_lookup(keys).result()

        def versions(self, keys):
            return self._cst.table.versions(keys)

    if hasattr(obj, "embedding_lookup") and hasattr(obj, "table"):
        return _CSTTier(obj)
    raise TypeError(
        f"host tier {type(obj).__name__} exposes neither lookup/versions "
        "nor the CacheSparseTable surface")


# one jitted scatter per (donate,) — jit caches per shape underneath;
# the fetch batch is padded to the next power of two (min 8) so a
# steady workload compiles a handful of variants, not one per distinct
# refresh count (padding repeats row 0: duplicate writes of identical
# bytes are benign under .at[].set)
_SCATTERS = {}


def _scatter_fn(donate):
    fn = _SCATTERS.get(donate)
    if fn is None:
        def scatter(rows_dev, slots, rows):
            return rows_dev.at[slots].set(rows)
        fn = jax.jit(scatter, donate_argnums=(0,) if donate else ())
        _SCATTERS[donate] = fn
    return fn


def _pad_pow2(arr, floor=8):
    m = arr.shape[0]
    b = floor
    while b < m:
        b *= 2
    if b == m:
        return arr
    return np.concatenate([arr, np.repeat(arr[:1], b - m, axis=0)])


class DeviceHotRowCache:
    """Hot-row tier over a host embedding table (see module doc).

    ``lookup_slots(ids)`` is the whole API surface the server needs: it
    returns the CACHE SLOT of every id (admitting/refreshing as needed,
    one host fetch + one device scatter per call), and
    ``packed_view()`` is the device array the jitted scorer gathers
    from with those slots."""

    def __init__(self, host_tier, cache_rows, dim, policy="lfu",
                 staleness_bound=0, name="hot", device=None,
                 dtype=jnp.float32):
        if policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {policy!r}")
        q = pack_factor(dim)
        if not q:
            raise ValueError(
                f"embedding dim {dim} does not pack into 128 lanes "
                "(the packed-lookup scoring path needs dim | 128)")
        if cache_rows < 1:
            raise ValueError(f"cache_rows must be >= 1, got {cache_rows}")
        if staleness_bound < 0:
            raise ValueError(
                f"staleness_bound must be >= 0, got {staleness_bound}")
        self.host = as_host_tier(host_tier)
        self.cache_rows = int(cache_rows)
        self.dim = int(dim)
        self.policy = policy
        self.staleness_bound = int(staleness_bound)
        self.name = str(name)
        self.device = device
        self.p_rows = packed_rows(self.cache_rows, self.dim)
        self.padded_rows = self.p_rows * q
        self.rows_dev = jnp.zeros((self.padded_rows, self.dim), dtype)
        if device is not None:
            self.rows_dev = jax.device_put(self.rows_dev, device)
        self._donate = jax.default_backend() != "cpu"
        # HBM accounting: the device hot tier is a fixed-size live buffer
        self._hbm_handle = _telemetry.get_hbm_ledger().alloc(
            "hot_cache", int(self.rows_dev.nbytes),
            owner=f"hot_cache:{self.name}:{id(self):x}")
        # host-side index: slot -> key/version/usage, key -> slot
        self.key_at = np.full(self.cache_rows, -1, np.int64)
        self.version_at = np.zeros(self.cache_rows, np.uint64)
        self.freq = np.zeros(self.cache_rows, np.int64)      # LFU
        self.stamp = np.zeros(self.cache_rows, np.int64)     # LRU
        self.slot_of = {}
        self._free = list(range(self.cache_rows - 1, -1, -1))
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.refreshes = 0
        self.evictions = 0
        self.host_rows_fetched = 0
        self.scatters = 0
        reg = _telemetry.get_registry()

        def _c(suffix, help):
            return reg.counter(f"hetu_embed_cache_{suffix}",
                               help, labels=("cache",)).labels(
                                   cache=self.name)

        self._m_hits = _c("hits_total",
                          "Rows served from the device hot tier")
        self._m_misses = _c("misses_total",
                            "Rows absent from the hot tier (admitted "
                            "from the host table)")
        self._m_refreshes = _c(
            "refreshes_total",
            "Cached rows past the staleness bound, force-refreshed")
        self._m_evictions = _c("evictions_total",
                               "Cache slots reclaimed from a colder row")
        self._m_occ = reg.gauge(
            "hetu_embed_cache_occupancy",
            "Occupied fraction of the device hot-row cache",
            labels=("cache",)).labels(cache=self.name)
        self._m_fetch = reg.histogram(
            "hetu_embed_host_fetch_seconds",
            "Host-tier row fetch latency (cold-tier reads on "
            "miss/refresh)", labels=("cache",),
            buckets=EMBED_BUCKETS).labels(cache=self.name)

    # -- views --------------------------------------------------------------
    def packed_view(self):
        """The device rows operand for the jitted scorer, which
        reshapes it in-graph to the packed ``[p_rows, 128]`` table
        ``packed_lookup`` gathers from (a free reshape — same bytes,
        ``[padded_rows, dim]`` row i IS packed logical row i)."""
        return self.rows_dev

    @property
    def occupancy(self):
        return 1.0 - len(self._free) / self.cache_rows

    @property
    def lookups(self):
        return self.hits + self.misses + self.refreshes

    @property
    def hit_rate(self):
        n = self.lookups
        return self.hits / n if n else 0.0

    def reset_stats(self):
        """Zero the hit/miss/refresh/eviction counters (NOT the cache
        contents or the registry mirror) — benches reset after warmup
        so the reported rates are steady-state serving, not compile
        and cold-fill."""
        self.hits = 0
        self.misses = 0
        self.refreshes = 0
        self.evictions = 0
        self.host_rows_fetched = 0
        self.scatters = 0

    def stats(self):
        return {"cache_rows": self.cache_rows,
                "hits": self.hits, "misses": self.misses,
                "refreshes": self.refreshes, "evictions": self.evictions,
                "hit_rate": round(self.hit_rate, 4),
                "occupancy": round(self.occupancy, 4),
                "host_rows_fetched": self.host_rows_fetched,
                "scatters": self.scatters,
                "policy": self.policy,
                "staleness_bound": self.staleness_bound}

    # -- the lookup ---------------------------------------------------------
    def _pick_victims(self, n, pinned):
        """``n`` occupied slots to reclaim, coldest first, never one of
        ``pinned`` (the slots this very batch will serve from)."""
        occupied = np.flatnonzero(self.key_at >= 0)
        if pinned:
            mask = np.ones(occupied.size, bool)
            pin = np.fromiter(pinned, np.int64, len(pinned))
            mask &= ~np.isin(occupied, pin)
            occupied = occupied[mask]
        if occupied.size < n:
            raise ValueError(
                f"batch needs {n} more cache slots but only "
                f"{occupied.size} are evictable — size the cache to at "
                "least one batch of unique ids (cache_rows >= "
                "n_slots * num_sparse)")
        if self.policy == "lfu":
            # least-frequently-used, oldest stamp breaking ties
            order = np.lexsort((self.stamp[occupied],
                                self.freq[occupied]))
        else:
            order = np.argsort(self.stamp[occupied], kind="stable")
        return occupied[order[:n]]

    def lookup_slots(self, ids):
        """Translate feature ids to cache slots, admitting misses and
        refreshing over-stale rows through one batched host fetch + one
        batched device scatter.  Returns int32 slots, same shape as
        ``ids``."""
        ids = np.asarray(ids)
        flat = np.ascontiguousarray(ids.reshape(-1), np.int64)
        uniq, inv = np.unique(flat, return_inverse=True)
        slots = np.empty(uniq.size, np.int64)
        cached_idx, missing_idx = [], []
        for i, key in enumerate(uniq):
            s = self.slot_of.get(int(key))
            if s is None:
                missing_idx.append(i)
            else:
                slots[i] = s
                cached_idx.append(i)
        stale_idx = []
        if cached_idx:
            c = np.asarray(cached_idx, np.int64)
            cur = self.host.versions(uniq[c])
            lag = cur - self.version_at[slots[c]]
            stale = lag > np.uint64(self.staleness_bound)
            stale_idx = list(c[stale])
            fresh = c[~stale]
            if fresh.size:
                self.hits += int(fresh.size)
                self._m_hits.inc(int(fresh.size))
                self.freq[slots[fresh]] += 1
                self.stamp[slots[fresh]] = self._tick
        pinned = set(int(s) for s in slots[np.asarray(cached_idx,
                                                      np.int64)]) \
            if cached_idx else set()
        if missing_idx:
            if len(missing_idx) > self.cache_rows:
                raise ValueError(
                    f"batch carries {len(missing_idx)} distinct uncached "
                    f"ids but the cache holds {self.cache_rows} rows — "
                    "size the cache to at least one batch of unique ids")
            need = []
            for i in missing_idx:
                if self._free:
                    need.append(self._free.pop())
                else:
                    need.append(None)
            short = sum(1 for s in need if s is None)
            if short:
                victims = self._pick_victims(short, pinned)
                self.evictions += int(victims.size)
                self._m_evictions.inc(int(victims.size))
                vi = iter(victims)
                for j, s in enumerate(need):
                    if s is None:
                        v = int(next(vi))
                        del self.slot_of[int(self.key_at[v])]
                        # the new tenant starts cold: inheriting the
                        # evictee's frequency would make every recycled
                        # slot look hot to LFU
                        self.freq[v] = 0
                        need[j] = v
            for i, s in zip(missing_idx, need):
                slots[i] = s
                pinned.add(int(s))
            self.misses += len(missing_idx)
            self._m_misses.inc(len(missing_idx))
        if stale_idx:
            self.refreshes += len(stale_idx)
            self._m_refreshes.inc(len(stale_idx))
        fetch_idx = list(missing_idx) + list(stale_idx)
        if fetch_idx:
            f = np.asarray(fetch_idx, np.int64)
            keys = uniq[f]
            t0 = time.perf_counter()
            # versions FIRST: a push landing between the two reads can
            # only leave version_at too old (earlier refresh), never
            # too new (a silently-overstale row)
            vers = self.host.versions(keys)
            rows = self.host.lookup(keys)
            self._m_fetch.observe(time.perf_counter() - t0)
            self.host_rows_fetched += int(keys.size)
            tgt = slots[f]
            self.rows_dev = _scatter_fn(self._donate)(
                self.rows_dev,
                jnp.asarray(_pad_pow2(tgt.astype(np.int32))),
                jnp.asarray(_pad_pow2(np.asarray(rows, np.float32))))
            self.scatters += 1
            self.key_at[tgt] = keys
            self.version_at[tgt] = vers
            for key, s in zip(keys, tgt):
                self.slot_of[int(key)] = int(s)
            self.freq[tgt] += 1
            self.stamp[tgt] = self._tick
        self._tick += 1
        self._m_occ.set(self.occupancy)
        return slots[inv].astype(np.int32).reshape(ids.shape)

    def gather_host(self, ids):
        """Serve ``ids`` and read the served rows back to the host —
        the bitwise-parity witness path (at ``staleness_bound=0`` the
        result must equal ``host.lookup(ids)`` exactly)."""
        slots = self.lookup_slots(ids).reshape(-1)
        return np.asarray(self.rows_dev)[slots]

    def close(self):
        """End the HBM-ledger accounting for the device tier
        (idempotent; the buffer itself is reclaimed by ordinary GC)."""
        self._hbm_handle.free()
