"""Tiered embedding serving (HET-style) behind the serving lifecycle.

Three tiers (PAPER.md's HET hot-embedding cache, re-hosted for TPU
serving):

1. **cold** — the host-RAM full table (`ps.EmbeddingTable` /
   `ps.CacheSparseTable`, optionally behind the PS RPC path);
2. **hot**  — :class:`DeviceHotRowCache`: a preallocated
   ``[cache_rows, dim]`` HBM array + host-side id→slot index with
   LFU/LRU admission and a bounded-staleness contract (a row may be
   served at most ``staleness_bound`` host-table updates stale before a
   forced refresh; bound 0 ⇒ bitwise parity with the host table),
   filled by BATCHED scatter, never per-row transfers;
3. **score** — one jitted program per server taking densified id
   batches through the ``ops/pallas/sparse_densify.py`` packed-lookup
   path into the ``models/ctr.py`` (WDL) dense layers.

:class:`EmbeddingServer` serves batched sparse-feature lookups + CTR
scoring through the SAME ``Scheduler`` lifecycle as LLM requests:
bounded-queue admission (typed ``EngineOverloaded``), deadlines/TTL,
``cancel()``, an in-graph finiteness sentinel, telemetry instruments,
and ``EngineFleet`` routing/failover (``engine_factory=
EmbeddingServer``) all work unchanged for microsecond-scale embedding
traffic.  ``bench.py --serve-embed`` replays a seeded Zipfian key trace
against an uncached host-tier twin.
"""

from .hot_cache import DeviceHotRowCache, EMBED_BUCKETS
from .server import BatchSlotPool, EmbedRequest, EmbeddingServer

__all__ = ["DeviceHotRowCache", "EmbeddingServer", "EmbedRequest",
           "BatchSlotPool", "EMBED_BUCKETS"]
