"""Tensor-parallel layouts for the serving engine (ISSUE 14).

One `(replica, model)` logical mesh per engine (``serving_mesh``), with
every block weight sharded on the **model** axis and the paged KV pool
sharded over its kv_heads axis.  The layouts are chosen so that the
sharded engine is a *bitwise* twin of the single-device engine — the
acceptance oracle for this layer:

* every weight matrix is sharded on an OUTPUT (non-contracting)
  dimension, so each device computes full-precision dot products over
  the complete contraction axis — no partial sums, no psum reordering;
* activations are gathered back to replicated (an all-gather moves
  bytes, exactly) before any op that reduces over a sharded axis
  (norms, the second projection of attention/MLP, sampling over
  logits).  The gather points live in ``models/*_decode.make_block``
  behind the ``gather=`` hook built by :func:`make_gather`.

This differs deliberately from the classic Megatron row-parallel
layout in ``parallel/tensor_parallel.py``: row-parallel's
psum-of-partials changes float reduction order and would break the
bitwise parity contract, so the second matmul of each pair shards its
output dim instead and the input is all-gathered.  Attention stays
genuinely head-parallel (q/k/v projections, rotary, softmax and the
weighted sum are all per-head local), and the KV page pool — the
dominant serving HBM consumer — is split ``kv_heads / tp`` per chip.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import make_mesh

# the KV page pool is [n_pages, layers, kv_heads, page_len, head_dim];
# kv_heads (axis 2) is the model-parallel axis — pages/slots stay
# replicated host-side so block tables and the allocator never change
KV_POOL_SPEC = P(None, None, "model", None, None)


def serving_mesh(tp, devices=None):
    """A ``(replica, model)`` mesh over ``tp`` devices.

    ``devices`` selects an explicit sub-mesh (the fleet pins one
    replica per contiguous device group); default is the first ``tp``
    of ``jax.devices()``.  The replica axis is always 1 here — fleet
    replication happens at the EngineFleet layer, not inside one
    engine's programs."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    devices = list(devices) if devices is not None else jax.devices()[:tp]
    if len(devices) < tp:
        raise ValueError(
            f"tensor-parallel degree {tp} needs {tp} devices, have "
            f"{len(devices)} (on CPU set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8)")
    return make_mesh({"replica": 1, "model": tp}, devices=devices[:tp])


def mesh_axis_size(mesh, axis="model"):
    return int(mesh.shape[axis])


def validate_tp(adapter, tp):
    """The head/ffn axes must divide evenly — a ragged shard would need
    padding inside the executables and break the parity oracle."""
    c = adapter.config
    bad = []
    if c.num_heads % tp:
        bad.append(f"num_heads={c.num_heads}")
    if adapter.kv_heads % tp:
        bad.append(f"kv_heads={adapter.kv_heads}")
    inter = getattr(c, "intermediate_size", None)
    if inter and inter % tp:
        bad.append(f"intermediate_size={inter}")
    if bad:
        raise ValueError(
            f"model axes not divisible by tp={tp}: {', '.join(bad)}")


def param_pspecs(adapter, params):
    """``{param_name: PartitionSpec}`` for every executor param the
    adapter consumes.  Unknown params (anything outside the decode
    naming contract, e.g. MoE routers) stay replicated — correctness
    first, sharding where the layout is pinned."""
    name = adapter.name
    layers = adapter.layers
    col = P(None, "model")          # shard the output dim
    specs = {k: P() for k in params}
    # class name check avoids an import cycle with adapters.py
    kind = type(adapter).__name__
    for i in range(layers):
        if kind == "LlamaSlotAdapter":
            our = f"{name}_layer{i}"
            for suffix in ("attn_q_weight", "attn_k_weight",
                           "attn_v_weight", "attn_out_weight",
                           "mlp_gate_weight", "mlp_up_weight",
                           "mlp_out_weight"):
                key = f"{our}_{suffix}"
                if key in specs:
                    specs[key] = col
        else:                       # GPT tier
            our = f"{name}_h{i}"
            for suffix in ("attn_q_weight", "attn_k_weight",
                           "attn_v_weight", "attn_out_weight",
                           "ffn_in_weight", "ffn_out_weight"):
                key = f"{our}_{suffix}"
                if key in specs:
                    specs[key] = col
            # a bias rides its matmul's sharded output dim
            for suffix in ("attn_q_bias", "attn_k_bias", "attn_v_bias",
                           "attn_out_bias", "ffn_in_bias",
                           "ffn_out_bias"):
                key = f"{our}_{suffix}"
                if key in specs:
                    specs[key] = P("model")
    # embeddings / norms / untied lm_head stay replicated: the head
    # matmul is a tiny fraction of decode FLOPs at serving vocab sizes
    # and a replicated head keeps sampling local and exact
    return specs


def param_shardings(mesh, adapter, params):
    """``{param_name: NamedSharding}`` for jit in_shardings."""
    return {k: NamedSharding(mesh, s)
            for k, s in param_pspecs(adapter, params).items()}


def kv_sharding(mesh):
    return NamedSharding(mesh, KV_POOL_SPEC)


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard_params(mesh, adapter, params):
    """Place a param dict on the mesh per :func:`param_pspecs`."""
    sh = param_shardings(mesh, adapter, params)
    return {k: jax.device_put(v, sh[k]) for k, v in params.items()}


def per_chip_bytes(tree):
    """Bytes resident per device for a (possibly sharded) array tree —
    the number the fleet's HBM headroom gating needs."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            per_dev = {}
            for s in shards:
                did = s.device.id
                per_dev[did] = per_dev.get(did, 0) + int(s.data.nbytes)
            total += max(per_dev.values())
        else:
            total += int(np.asarray(leaf).nbytes)
    return total


def device_ids(mesh):
    return tuple(int(d.id) for d in mesh.devices.flat)
