"""Slot-batched views of the KV-cache decoders for the serving engine.

The one-shot decoders (models/llama_decode.py, gpt_decode.py) carry
their K/V cache through a ``lax.scan`` with ONE shared position for the
whole batch — fine for a fixed batch, useless for continuous batching
where every slot sits at a different sequence position.  An adapter
re-hosts the SAME per-layer block math (imported from those modules, not
copied) in slot-batched form:

* ``decode(params, tokens [S], positions [S], k, v)`` — one token per
  slot, each at its own position, against the pooled cache
  ``[S, L, KV, T, D]``.  The per-slot position plumbing (rotary angles,
  attention mask, cache write offset) is vmapped over the slot axis, so
  per-slot ``dynamic_update_slice`` writes lower to one batched scatter.
* ``prefill(params, prompt [1, P])`` — a whole prompt through all
  layers at once, returning the per-layer K/V to deposit into one slot
  plus the logits row that seeds the first generated token.
* ``prefill_chunk(params, tokens [B, C], starts [B], k, v)`` — the
  paged engine's batched AND chunked prefill: lane ``i`` pushes chunk
  rows ``[starts[i], starts[i] + C)`` of its prompt through all layers
  against its own gathered cache ``[B, L, KV, T', D]``.  The engine
  pads the gathered time axis by C before calling (so the in-block
  ``dynamic_update_slice`` at ``start`` can never clamp) and routes
  the pad rows' page-pool write-back to the sentinel page.

All are pure functions of static shapes: the engine jits them once
(per ``[B, C]`` bucket for the chunk path).

Pad-safety: prefill pads prompts to the engine's fixed bucket P and
also returns K/V for the pad tail.  That tail is harmless — decode
masks attention to ``col <= position`` and every cache row between the
true prompt length and the current position has been overwritten by a
decode step before it first becomes attendable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.rotary import _rope_tables
from ..models import llama_decode as _ld
from ..models import gpt_decode as _gd
from ..models._decode_common import make_gather


def _causal(p_len):
    return (jnp.arange(p_len)[None, :] <= jnp.arange(p_len)[:, None])


class LlamaSlotAdapter:
    """Rotary/GQA (Llama-family, incl. sparse-MoE) slot-batched decode."""

    def __init__(self, config, name, moe_names=None, mesh=None,
                 gather_dtype=None):
        c = config
        self.config = c
        self.name = name
        self.mesh = mesh
        self.layers = c.num_layers
        self.kv_heads = c.num_kv_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.position_cap = None          # rotary: no learned-table limit
        self.embed_param = f"{name}_embed_table"
        gather = (make_gather(mesh, quant_dtype=gather_dtype)
                  if mesh is not None else None)
        self._layer_params = _ld.make_layer_params(c, name, moe_names)
        self._block = _ld.make_block(c, gather=gather)
        self._logits = _ld.make_logits(c, name)
        self._chunk_inputs = _ld.make_chunk_embed(c, name)

    @classmethod
    def for_model(cls, model, name, mesh=None, gather_dtype=None):
        return cls(model.config, name,
                   moe_names=_ld.moe_param_names(model), mesh=mesh,
                   gather_dtype=gather_dtype)

    def decode(self, params, tokens, positions, k, v, n_layers=None):
        """Slot-batched decode (see module doc).  ``n_layers`` truncates
        the stack to its first N blocks — the speculative self-draft
        path: the caller passes caches sliced to ``[:, :N]`` and the
        truncated trunk feeds the full final-norm/LM head, so draft
        logits cost N/L of a target step with zero extra parameters."""
        c, hd = self.config, self.head_dim
        nl = self.layers if n_layers is None else int(n_layers)
        emb = params[self.embed_param]
        lps = [self._layer_params(params, i) for i in range(nl)]
        max_len = k.shape[3]
        cos_t, sin_t = _rope_tables(max_len, hd, c.rope_theta)
        x = emb[tokens][:, None, None, :]            # [S, 1, 1, H]
        cos = cos_t[positions][:, None, :]           # [S, 1, hd]
        sin = sin_t[positions][:, None, :]
        mask = (jnp.arange(max_len)[None, :]
                <= positions[:, None])[:, None, :]   # [S, 1, T]
        vblock = jax.vmap(self._block,
                          in_axes=(None, 0, 0, 0, 0, 0, 0, 0))
        ks, vs = [], []
        for i, lp in enumerate(lps):
            ck, cv = k[:, i][:, None], v[:, i][:, None]  # [S, 1, KV, T, D]
            x, ck, cv = vblock(lp, x, ck, cv, cos, sin, mask, positions)
            ks.append(ck[:, 0])
            vs.append(cv[:, 0])
        logits = self._logits(params, x[:, 0, 0, :])     # [S, V]
        return logits, jnp.stack(ks, 1), jnp.stack(vs, 1)

    def prefill(self, params, prompt):
        c, hd = self.config, self.head_dim
        emb = params[self.embed_param]
        lps = [self._layer_params(params, i) for i in range(self.layers)]
        _, p_len = prompt.shape
        cos_t, sin_t = _rope_tables(p_len, hd, c.rope_theta)
        x = emb[prompt]
        mask = _causal(p_len)
        kshape = (1, self.kv_heads, p_len, hd)
        ks, vs = [], []
        for lp in lps:
            ck = jnp.zeros(kshape, emb.dtype)
            cv = jnp.zeros(kshape, emb.dtype)
            x, ck, cv = self._block(lp, x, ck, cv, cos_t, sin_t, mask, 0)
            ks.append(ck[0])
            vs.append(cv[0])
        logits = self._logits(params, x[0])              # [P, V]
        return logits, jnp.stack(ks), jnp.stack(vs)

    def prefill_chunk(self, params, tokens, starts, k, v):
        """Batched chunked prefill (see module doc): ``tokens [B, C]``
        against per-lane caches ``k, v [B, L, KV, T', D]`` with lane
        write offsets ``starts [B]``.  Returns ``(logits [B, C, V],
        k', v')`` with the chunk's K/V written at rows
        ``[start, start + C)``."""
        lps = [self._layer_params(params, i) for i in range(self.layers)]
        t = k.shape[3]
        x, cos, sin, mask = self._chunk_inputs(params, tokens, starts, t)
        x = x[:, None]                                   # [B, 1, C, H]
        vblock = jax.vmap(self._block,
                          in_axes=(None, 0, 0, 0, 0, 0, 0, 0))
        ks, vs = [], []
        for i, lp in enumerate(lps):
            ck, cv = k[:, i][:, None], v[:, i][:, None]  # [B, 1, KV, T', D]
            x, ck, cv = vblock(lp, x, ck, cv, cos, sin, mask, starts)
            ks.append(ck[:, 0])
            vs.append(cv[:, 0])
        logits = self._logits(params, x[:, 0])           # [B, C, V]
        return logits, jnp.stack(ks, 1), jnp.stack(vs, 1)


class GPTSlotAdapter:
    """Learned-positions GPT slot-batched decode.  The position table
    caps total sequence length at ``config.seq_len`` — the engine
    enforces ``max_len <= seq_len`` via ``position_cap``."""

    def __init__(self, config, name, mesh=None, gather_dtype=None):
        c = config
        self.config = c
        self.name = name
        self.mesh = mesh
        self.layers = c.num_layers
        self.kv_heads = c.num_heads       # no GQA in the GPT tier
        self.head_dim = c.hidden_size // c.num_heads
        self.position_cap = c.seq_len
        self.embed_param = f"{name}_wte_table"
        gather = (make_gather(mesh, quant_dtype=gather_dtype)
                  if mesh is not None else None)
        self._layer_params = _gd.make_layer_params(c, name)
        self._block = _gd.make_block(c, gather=gather)
        self._logits = _gd.make_logits(c, name)
        self._chunk_inputs = _gd.make_chunk_embed(c, name)

    @classmethod
    def for_model(cls, model, name, mesh=None, gather_dtype=None):
        return cls(model.config, name, mesh=mesh,
                   gather_dtype=gather_dtype)

    def decode(self, params, tokens, positions, k, v, n_layers=None):
        nl = self.layers if n_layers is None else int(n_layers)
        emb = params[self.embed_param]
        wpe = params[f"{self.name}_wpe"]
        lps = [self._layer_params(params, i) for i in range(nl)]
        max_len = k.shape[3]
        x = (emb[tokens] + wpe[positions])[:, None, None, :]  # [S, 1, 1, H]
        mask = (jnp.arange(max_len)[None, :]
                <= positions[:, None])[:, None, :]            # [S, 1, T]
        vblock = jax.vmap(self._block, in_axes=(None, 0, 0, 0, 0, 0))
        ks, vs = [], []
        for i, lp in enumerate(lps):
            ck, cv = k[:, i][:, None], v[:, i][:, None]
            x, ck, cv = vblock(lp, x, ck, cv, mask, positions)
            ks.append(ck[:, 0])
            vs.append(cv[:, 0])
        logits = self._logits(params, x[:, 0, 0, :])
        return logits, jnp.stack(ks, 1), jnp.stack(vs, 1)

    def prefill(self, params, prompt):
        emb = params[self.embed_param]
        wpe = params[f"{self.name}_wpe"]
        lps = [self._layer_params(params, i) for i in range(self.layers)]
        _, p_len = prompt.shape
        x = emb[prompt] + wpe[None, :p_len]
        mask = _causal(p_len)
        kshape = (1, self.kv_heads, p_len, self.head_dim)
        ks, vs = [], []
        for lp in lps:
            ck = jnp.zeros(kshape, emb.dtype)
            cv = jnp.zeros(kshape, emb.dtype)
            x, ck, cv = self._block(lp, x, ck, cv, mask, 0)
            ks.append(ck[0])
            vs.append(cv[0])
        logits = self._logits(params, x[0])
        return logits, jnp.stack(ks), jnp.stack(vs)

    def prefill_chunk(self, params, tokens, starts, k, v):
        """Batched chunked prefill, GPT flavor (learned positions are
        added at embedding time by the chunk-input helper)."""
        lps = [self._layer_params(params, i) for i in range(self.layers)]
        t = k.shape[3]
        x, mask = self._chunk_inputs(params, tokens, starts, t)
        x = x[:, None]                                   # [B, 1, C, H]
        vblock = jax.vmap(self._block, in_axes=(None, 0, 0, 0, 0, 0))
        ks, vs = [], []
        for i, lp in enumerate(lps):
            ck, cv = k[:, i][:, None], v[:, i][:, None]
            x, ck, cv = vblock(lp, x, ck, cv, mask, starts)
            ks.append(ck[:, 0])
            vs.append(cv[:, 0])
        logits = self._logits(params, x[:, 0])           # [B, C, V]
        return logits, jnp.stack(ks, 1), jnp.stack(vs, 1)


def adapter_for(model, name, mesh=None, gather_dtype=None):
    """Pick the slot adapter matching a model instance by its config
    family (rotary Llama-likes vs learned-position GPTs).  ``mesh``
    (tensor-parallel serving) threads the replicate-back hook into the
    block math — see serving/sharding.py.  ``gather_dtype`` quantizes
    those gathers through the shared codec (ops/quant.py); None keeps
    the bitwise replicate-back."""
    c = model.config
    if hasattr(c, "rope_theta"):
        return LlamaSlotAdapter.for_model(model, name, mesh=mesh,
                                          gather_dtype=gather_dtype)
    if hasattr(c, "seq_len") and hasattr(c, "num_layers"):
        return GPTSlotAdapter.for_model(model, name, mesh=mesh,
                                        gather_dtype=gather_dtype)
    raise TypeError(
        f"no slot adapter for {type(model).__name__} "
        f"(config {type(c).__name__}) — serving supports the Llama and "
        "GPT KV-cache decoder tiers")
