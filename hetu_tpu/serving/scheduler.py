"""Iteration-level (continuous-batching) request scheduler.

One scheduler iteration == one engine step: first ADMIT queued requests
into free KV slots (FIFO, at most ``prefill_budget`` prefills per
iteration so admission can't starve in-flight decode latency), then the
engine runs ONE slot-batched decode step for everything in flight.  A
request that finishes (EOS or max_new) retires immediately and its slot
goes back to the pool, so the next queued request is admitted on the
very next iteration — mid-flight, without waiting for the rest of the
batch.  This is the orca/vLLM iteration-level scheduling idea with the
TPU twist that the step shape never changes (empty slots are masked
no-ops, not absent).

``gang=True`` turns the same machinery into the static-batching
baseline twin the serve bench compares against: admission waits until
EVERY slot is free, then fills the whole pool at once — requests that
finish early leave their slots idle until the stragglers drain, exactly
the occupancy collapse continuous batching removes."""

from __future__ import annotations

import itertools
from collections import deque

import numpy as np

from .. import telemetry as _telemetry


class Request:
    """One generation request and its lifecycle timestamps."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new, arrival=None, stream=None,
                 eos_id=None):
        self.rid = next(self._ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.max_new = int(max_new)
        self.stream = stream
        self.eos_id = eos_id
        self.tokens = []          # generated ids, prompt excluded
        self.slot = None
        self.finished = False
        self.finish_reason = None   # "eos" | "max_new"
        # lifecycle clocks (engine fills these from its monotonic clock)
        self.t_arrival = arrival
        self.t_admit = None       # prefill start == queue exit
        self.t_first = None       # first token produced (prefill end)
        self.t_done = None

    # -- latency views (None until the corresponding edge has passed) ------
    @property
    def queue_wait(self):
        if self.t_admit is None or self.t_arrival is None:
            return None
        return self.t_admit - self.t_arrival

    @property
    def ttft(self):
        if self.t_first is None or self.t_arrival is None:
            return None
        return self.t_first - self.t_arrival

    @property
    def tpot(self):
        """Mean time per output token AFTER the first (the decode-rate
        metric); 0.0 for single-token requests."""
        if self.t_done is None or self.t_first is None:
            return None
        n = len(self.tokens)
        return (self.t_done - self.t_first) / (n - 1) if n > 1 else 0.0

    def result(self):
        return np.asarray(self.tokens, np.int32)

    def __repr__(self):
        state = ("done" if self.finished
                 else "running" if self.slot is not None else "queued")
        return (f"Request(id={self.rid}, prompt={self.prompt.size}, "
                f"max_new={self.max_new}, {state})")


class Scheduler:
    """FIFO admission over a SlotKVCache pool."""

    def __init__(self, cache, prefill_budget=2, gang=False):
        if prefill_budget < 1:
            raise ValueError(
                f"prefill_budget must be >= 1, got {prefill_budget}")
        self.cache = cache
        self.prefill_budget = int(prefill_budget)
        self.gang = bool(gang)
        self.queue = deque()
        self.running = {}           # slot -> Request
        self.admitted_order = []    # rids in prefill order (FIFO witness)
        mode = "gang" if self.gang else "continuous"
        reg = _telemetry.get_registry()
        self._m_queue = reg.gauge(
            "hetu_serving_queue_depth",
            "Requests waiting for a KV slot",
            labels=("scheduler",)).labels(scheduler=mode)
        self._m_admitted = reg.counter(
            "hetu_serving_admissions_total",
            "Requests admitted into a slot",
            labels=("scheduler",)).labels(scheduler=mode)

    def submit(self, request):
        self.queue.append(request)
        self._m_queue.set(len(self.queue))
        return request

    @property
    def idle(self):
        return not self.queue and not self.running

    def admit(self):
        """Move queued requests into free slots; returns the admitted
        [(request, slot)] for the engine to prefill, FIFO order."""
        out = []
        if self.gang and self.cache.n_active > 0:
            return out   # static batching: wait for the batch to drain
        budget = self.cache.n_slots if self.gang else self.prefill_budget
        while self.queue and len(out) < budget:
            req = self.queue[0]
            slot = self.cache.alloc(owner=req.rid)
            if slot is None:
                break
            self.queue.popleft()
            req.slot = slot
            self.running[slot] = req
            self.admitted_order.append(req.rid)
            out.append((req, slot))
        if out:
            self._m_queue.set(len(self.queue))
            self._m_admitted.inc(len(out))
        return out

    def retire(self, request, reason):
        """Release a finished request's slot back to the pool."""
        slot = request.slot
        if slot is None or self.running.get(slot) is not request:
            raise RuntimeError(f"retire of non-running {request!r}")
        request.finished = True
        request.finish_reason = reason
        del self.running[slot]
        request.slot = None
        self.cache.free(slot)

    def active_slots(self):
        return sorted(self.running)
