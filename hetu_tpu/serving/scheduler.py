"""Iteration-level (continuous-batching) request scheduler.

One scheduler iteration == one engine step: first ADMIT queued requests
into free KV slots (FIFO, at most ``prefill_budget`` prefills per
iteration so admission can't starve in-flight decode latency), then the
engine runs ONE slot-batched decode step for everything in flight.  A
request that finishes (EOS or max_new) retires immediately and its slot
goes back to the pool, so the next queued request is admitted on the
very next iteration — mid-flight, without waiting for the rest of the
batch.  This is the orca/vLLM iteration-level scheduling idea with the
TPU twist that the step shape never changes (empty slots are masked
no-ops, not absent).

``gang=True`` turns the same machinery into the static-batching
baseline twin the serve bench compares against: admission waits until
EVERY slot is free, then fills the whole pool at once — requests that
finish early leave their slots idle until the stragglers drain, exactly
the occupancy collapse continuous batching removes.

Admission control (``max_queue``): production engines die by queue, not
by compute — an arrival burst that outruns decode grows the waiting
line without bound until every queued request is past its deadline and
the host is out of memory.  A bounded queue with watermark hysteresis
sheds load at the door instead: once depth hits ``max_queue`` the
scheduler REJECTS new work (typed :class:`EngineOverloaded`, carrying
the depth so clients can back off) until the queue drains to
``low_watermark`` — the hysteresis stops the accept/reject flapping a
single hard bound produces at saturation.  Two documented shed
policies:

* ``"reject_newest"`` (default) — the incoming request is refused;
  everything already queued keeps its FIFO position.  Predictable for
  clients (admission is decided at submit time, never revoked) and the
  right default when requests have no deadlines.
* ``"drop_expired_first"`` — before refusing, queued requests whose
  deadline has already passed are shed (they would be expired at
  admission anyway and are only holding seats); the incoming request is
  refused only if the queue is still full.  Strictly better goodput
  when deadlines are in play — a seat held by a dead request serves
  nobody.
"""

from __future__ import annotations

import itertools
from collections import deque

import numpy as np

from .. import telemetry as _telemetry

#: every terminal state a request can reach.  "eos"/"max_new" are the
#: healthy LLM terminals and "scored" the healthy EMBEDDING one (an
#: EmbeddingServer request completes in a single batched
#: lookup+score iteration); "deadline" (TTL passed — at admission or
#: mid-flight), "cancelled" (engine.cancel / scheduler shed), and
#: "error" (decode watchdog quarantined the slot) all return whatever
#: tokens were produced so far as a PARTIAL result.  "failover" is
#: terminal only for the ENGINE-LEVEL attempt: the fleet harvested the
#: request off this engine (crash/quarantine/wedge) and the same rid
#: continues on a sibling — cluster-level, the request is still live.
FINISH_REASONS = ("eos", "max_new", "scored", "deadline", "cancelled",
                  "error", "failover")

#: the healthy terminals — what a fleet treats as "this attempt
#: SUCCEEDED" (everything else is a partial, a refusal, or a fault)
TERMINAL_OK = ("eos", "max_new", "scored")

SHED_POLICIES = ("reject_newest", "drop_expired_first")


class EngineOverloaded(RuntimeError):
    """Admission refused: the request queue is at (or draining from) its
    bound.  Carries ``queue_depth``/``max_queue`` so a client can size
    its backoff instead of guessing."""

    def __init__(self, queue_depth, max_queue):
        super().__init__(
            f"engine overloaded: {queue_depth} requests queued "
            f"(max_queue={max_queue}) — retry after the queue drains")
        self.queue_depth = int(queue_depth)
        self.max_queue = int(max_queue)


class Request:
    """One generation request and its lifecycle timestamps.

    ``rid`` is assigned by the scheduler at submit time (ids are scoped
    PER SCHEDULER, not process-global: two engines each number their
    requests 0, 1, 2, …, so id-keyed records are deterministic per run
    and never collide across engines or leak across tests).  A scheduler
    built with ``rid_prefix=`` mints CLUSTER-LEVEL ids ("e0-0", "e0-1",
    …) so a fleet's records name the engine instance that admitted each
    request; a pre-assigned ``rid=`` (a fleet failing a request over to
    a sibling) is kept as-is.

    ``replay=`` carries tokens a previous attempt already generated (and
    delivered): the engine rebuilds the KV state by teacher-forcing them
    — prefill + one decode step per replayed token through the SAME
    shared executables — without re-emitting them, so a failed-over
    greedy stream continues bitwise identically where it left off.
    """

    def __init__(self, prompt, max_new, arrival=None, stream=None,
                 eos_id=None, deadline=None, replay=None, rid=None,
                 temperature=None, top_k=None, seed=None):
        self.rid = rid            # scheduler-scoped, set on submit
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.max_new = int(max_new)
        self.stream = stream
        self.eos_id = eos_id
        # per-request sampling overrides (paged engines thread these as
        # decode operands; None = use the engine's defaults)
        self.temperature = (None if temperature is None
                            else float(temperature))
        self.top_k = None if top_k is None else int(top_k)
        self.seed = None if seed is None else int(seed)
        # absolute deadline on the engine's monotonic clock; None = no TTL
        self.deadline = None if deadline is None else float(deadline)
        if replay is None:
            self.replay = None
        else:
            self.replay = np.asarray(replay, np.int32).reshape(-1)
            if self.replay.size >= self.max_new:
                raise ValueError(
                    f"replay carries {self.replay.size} tokens but "
                    f"max_new={self.max_new} — the request was already "
                    "complete")
        self._replay_pos = 0
        self.tokens = []          # generated ids, prompt excluded
        self.slot = None
        self.finished = False
        self.finish_reason = None   # one of FINISH_REASONS
        self.cancel_requested = False
        # lifecycle clocks (engine fills these from its monotonic clock)
        self.t_arrival = arrival
        self.t_admit = None       # prefill start == queue exit
        self.t_first = None       # first token produced (prefill end)
        self.t_done = None

    def expired(self, now):
        return self.deadline is not None and now >= self.deadline

    # -- failover replay ----------------------------------------------------
    @property
    def replaying(self):
        """True while tokens from a previous attempt remain to rebuild."""
        return (self.replay is not None
                and self._replay_pos < self.replay.size)

    def next_replay(self):
        """The next token to teacher-force (consuming it), or None once
        the replay is exhausted and decoding continues live."""
        if not self.replaying:
            return None
        tok = int(self.replay[self._replay_pos])
        self._replay_pos += 1
        return tok

    # -- latency views (None until the corresponding edge has passed) ------
    @property
    def queue_wait(self):
        if self.t_admit is None or self.t_arrival is None:
            return None
        return self.t_admit - self.t_arrival

    @property
    def ttft(self):
        if self.t_first is None or self.t_arrival is None:
            return None
        return self.t_first - self.t_arrival

    @property
    def tpot(self):
        """Mean time per output token AFTER the first (the decode-rate
        metric); 0.0 for single-token requests."""
        if self.t_done is None or self.t_first is None:
            return None
        n = len(self.tokens)
        return (self.t_done - self.t_first) / (n - 1) if n > 1 else 0.0

    def result(self):
        return np.asarray(self.tokens, np.int32)

    def __repr__(self):
        state = ("done" if self.finished
                 else "running" if self.slot is not None else "queued")
        return (f"Request(id={self.rid}, prompt={self.prompt.size}, "
                f"max_new={self.max_new}, {state})")


class Scheduler:
    """FIFO admission over a SlotKVCache pool, with an optional bounded
    queue (``max_queue`` + watermark hysteresis, see module doc)."""

    def __init__(self, cache, prefill_budget=2, gang=False,
                 max_queue=None, low_watermark=None,
                 shed_policy="reject_newest", rid_prefix=None,
                 lookahead=0):
        if prefill_budget < 1:
            raise ValueError(
                f"prefill_budget must be >= 1, got {prefill_budget}")
        if shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"shed_policy must be one of {SHED_POLICIES}, got "
                f"{shed_policy!r}")
        self.cache = cache
        self.prefill_budget = int(prefill_budget)
        self.gang = bool(gang)
        self.max_queue = None if max_queue is None else int(max_queue)
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(
                f"max_queue must be >= 1, got {self.max_queue}")
        if low_watermark is None:
            # drain to half before reopening — enough hysteresis to stop
            # flapping without holding the door shut for a full drain
            self.low_watermark = (None if self.max_queue is None
                                  else max(0, self.max_queue // 2))
        else:
            self.low_watermark = int(low_watermark)
            if (self.max_queue is not None
                    and not 0 <= self.low_watermark < self.max_queue):
                raise ValueError(
                    f"low_watermark={self.low_watermark} must be in "
                    f"[0, max_queue={self.max_queue})")
        self.shed_policy = shed_policy
        # speculative lookahead: extra per-request token reservation so
        # a verify window (k candidates past the newest position) can
        # never scatter outside the slot's pages — admission stays the
        # only refusal point (engine passes spec_k)
        self.lookahead = int(lookahead)
        if self.lookahead < 0:
            raise ValueError(
                f"lookahead must be >= 0, got {self.lookahead}")
        # prefix-cache hook (engine-installed): prompt -> (pages,
        # n_tokens) of an interned prefix to share into the new slot,
        # or None on a miss
        self.prefix_lookup = None
        self.queue = deque()
        self.running = {}           # slot -> Request
        self.admitted_order = []    # rids in prefill order (FIFO witness)
        self._ids = itertools.count()   # rid source, scoped to THIS scheduler
        # cluster-level ids: "e0-0", "e0-1", … name the engine instance
        self.rid_prefix = None if rid_prefix is None else str(rid_prefix)
        self._shedding = False      # watermark hysteresis state
        self.shed = []              # expired requests shed at submit
        self.rejected = 0
        self.queue_depth_peak = 0
        mode = "gang" if self.gang else "continuous"
        reg = _telemetry.get_registry()
        self._m_queue = reg.gauge(
            "hetu_serving_queue_depth",
            "Requests waiting for a KV slot",
            labels=("scheduler",)).labels(scheduler=mode)
        self._m_queue_peak = reg.gauge(
            "hetu_serving_queue_depth_peak",
            "High watermark of the request queue depth",
            labels=("scheduler",)).labels(scheduler=mode)
        self._m_admitted = reg.counter(
            "hetu_serving_admissions_total",
            "Requests admitted into a slot",
            labels=("scheduler",)).labels(scheduler=mode)
        self._m_rejected = reg.counter(
            "hetu_serving_rejections_total",
            "Requests refused at admission (EngineOverloaded)",
            labels=("scheduler",)).labels(scheduler=mode)
        self._rt = _telemetry.get_request_trace()

    # -- admission control --------------------------------------------------
    def _admission_open(self):
        """Bounded-queue watermark hysteresis: closed from the moment
        depth hits ``max_queue`` until it drains to ``low_watermark``."""
        if self.max_queue is None:
            return True
        depth = len(self.queue)
        if self._shedding:
            if depth <= self.low_watermark:
                self._shedding = False
                return True
            return False
        if depth >= self.max_queue:
            self._shedding = True
            return False
        return True

    def take_expired(self, now):
        """Remove and return every QUEUED request whose deadline has
        passed (the engine finalizes them with reason "deadline" —
        partial result: zero tokens, never admitted)."""
        if not self.queue:
            return []
        expired = [r for r in self.queue if r.expired(now)]
        if expired:
            self.queue = deque(r for r in self.queue
                               if not r.expired(now))
            self._m_queue.set(len(self.queue))
        return expired

    def submit(self, request, now=None):
        """Assign a scheduler-scoped rid and enqueue, or raise
        :class:`EngineOverloaded` when the bounded queue refuses it
        (after shedding expired seats under ``drop_expired_first``)."""
        if not self._admission_open():
            if (self.shed_policy == "drop_expired_first"
                    and now is not None):
                # expired seats serve nobody: shed them before refusing
                # live work (the engine collects them via drain_shed and
                # records them with reason "deadline").  Freed seats
                # reopen admission immediately — the hysteresis exists
                # to stop flapping under LIVE load, not to refuse work
                # while dead seats are being vacated.
                dropped = self.take_expired(now)
                if dropped:
                    self.shed.extend(dropped)
                    if len(self.queue) < self.max_queue:
                        self._shedding = False
            if not self._admission_open():
                self.rejected += 1
                self._m_rejected.inc()
                raise EngineOverloaded(len(self.queue), self.max_queue)
        if request.rid is None:
            n = next(self._ids)
            request.rid = (n if self.rid_prefix is None
                           else f"{self.rid_prefix}-{n}")
        self.queue.append(request)
        depth = len(self.queue)
        # accepted: the timeline for this rid starts (or, on a fleet
        # failover re-submit of the same rid, CONTINUES) here
        self._rt.event(request.rid, "queued", engine=self.rid_prefix,
                       deadline=request.deadline, depth=depth)
        self._m_queue.set(depth)
        if depth > self.queue_depth_peak:
            self.queue_depth_peak = depth
            self._m_queue_peak.set(depth)
        return request

    def drain_shed(self):
        """Requests ``submit`` shed under ``drop_expired_first`` since
        the last call — the engine finalizes + records them."""
        shed, self.shed = self.shed, []
        return shed

    @property
    def idle(self):
        return not self.queue and not self.running

    def backlog(self):
        """Outstanding token debt, for predictive admission: the
        generated-token budget still owed to queued requests (their
        whole ``max_new``) and running ones (what's left of it)."""
        queued = sum(r.max_new for r in self.queue)
        running = sum(max(0, r.max_new - len(r.tokens))
                      for r in self.running.values())
        return {"depth": len(self.queue) + len(self.running),
                "queued_tokens": int(queued),
                "running_tokens": int(running)}

    def admit(self, token_budget=None):
        """Move queued requests into free slots; returns the admitted
        [(request, slot)] for the engine to prefill, FIFO order.

        ``token_budget`` (paged engines) additionally caps the PROMPT
        tokens admitted this iteration — the chunked-prefill knob that
        keeps one long prompt from stalling in-flight decode.  Slot
        allocation passes each request's worst-case token need
        (prompt + max_new) so a paged pool reserves pages up front and
        can never run out mid-flight."""
        out = []
        if self.gang and self.cache.n_active > 0:
            return out   # static batching: wait for the batch to drain
        budget = self.cache.n_slots if self.gang else self.prefill_budget
        used_tokens = 0
        while self.queue and len(out) < budget:
            req = self.queue[0]
            if (token_budget is not None
                    and used_tokens + int(req.prompt.size) > token_budget
                    and out):
                break   # FIFO: don't skip ahead past a too-long prompt
            shared, shared_tokens = None, 0
            if self.prefix_lookup is not None:
                hit = self.prefix_lookup(req.prompt)
                if hit is not None:
                    shared, shared_tokens = hit
            alloc_kw = {"shared": shared} if shared is not None else {}
            slot = self.cache.alloc(owner=req.rid,
                                    n_tokens=(int(req.prompt.size)
                                              + req.max_new
                                              + self.lookahead),
                                    **alloc_kw)
            if slot is None:
                break
            req.prefix_tokens = shared_tokens
            used_tokens += int(req.prompt.size) - shared_tokens
            self.queue.popleft()
            req.slot = slot
            self.running[slot] = req
            self.admitted_order.append(req.rid)
            out.append((req, slot))
        if out:
            self._m_queue.set(len(self.queue))
            self._m_admitted.inc(len(out))
        return out

    def retire(self, request, reason):
        """Release a finished request's slot back to the pool."""
        slot = request.slot
        if slot is None or self.running.get(slot) is not request:
            raise RuntimeError(f"retire of non-running {request!r}")
        request.finished = True
        request.finish_reason = reason
        del self.running[slot]
        request.slot = None
        self.cache.free(slot)

    def remove_queued(self, request):
        """Drop a still-queued request (cancellation); False if it was
        not in the queue (already admitted or finished)."""
        try:
            self.queue.remove(request)
        except ValueError:
            return False
        self._m_queue.set(len(self.queue))
        return True

    def find(self, rid):
        """The live (queued or running) request with this rid, or None."""
        for req in self.running.values():
            if req.rid == rid:
                return req
        for req in self.queue:
            if req.rid == rid:
                return req
        return None

    def reconcile(self):
        """Free cache slots owned by nobody (a leaked slot: allocated
        but absent from ``running``).  A healthy scheduler never has
        any; after a fault (or injected leak) this returns the pool to
        balance instead of letting the engine starve.  Returns the
        number of slots reclaimed."""
        leaked = [s for s in self.cache.allocated_slots()
                  if s not in self.running]
        for s in leaked:
            self.cache.free(s)
        return len(leaked)

    def active_slots(self):
        return sorted(self.running)
