"""TPU-native continuous-batching inference engine.

``InferenceEngine`` wraps an Executor-trained (or HF-imported) decode
model into exactly TWO jitted programs whose shapes never change:

* ``prefill(params, k, v, prompt [1, P], p_len, slot, key)`` — run one
  prompt (padded to the fixed bucket P = ``max_prompt_len``) through all
  layers, deposit its K/V into ``slot`` of the pooled cache, and emit
  the request's first token from the true last prompt row;
* ``step(params, k, v, tokens [S], positions [S], active [S], key)`` —
  ONE decode iteration for every slot at once, each slot at its own
  position (adapters.py vmaps the per-layer block over slots).  Inactive
  slots compute masked garbage — the price of a static shape — and
  their outputs are discarded host-side.

``paged=True`` swaps the dense ``SlotKVCache`` for a
:class:`~.kv_cache.PagedKVCache` (fixed page pool + per-slot block
tables) and rebuilds both programs around an in-graph page gather:

* decode gains block-table + per-slot sampling operands
  (``tables [S, max_pages]``, ``temps/top_ks/seeds [S]``) — slot
  capacity and sampling become data, not compile-time constants, so
  the compile-once contract is untouched by request mix;
* prefill becomes BATCHED and CHUNKED: every admitted prompt chunk in
  one padded ``[B, C]`` call (both axes pow2-bucketed to bound compile
  variants), long prompts split across iterations under
  ``prefill_token_budget`` so decode interleaves between chunks
  instead of stalling behind one long prompt.

Sampling keys derive in-graph from ``fold_in(fold_in(key(0), seed),
consumed)`` — per request, not per engine — so a sampled stream at a
fixed seed is deterministic and continues bit-exactly through fleet
failover replay.  Greedy lanes run the identical argmax as the slot
engine: the paged twin's greedy streams are bitwise equal to the
dense twin's (the serve bench asserts it).

Both programs also return a FINITENESS SENTINEL computed in-graph (the
StepGuard idea from the training path, re-hosted per slot): ``prefill``
returns one ok scalar for its logits row, ``step`` returns a per-slot
ok vector.  The sentinel rides the same fusion as the logits reduction,
so the protected and unprotected engines run the SAME executable — the
watchdog is a host-side decision about what to do with the bit, not a
different program.

Failure surface (all enabled by default, see the ctor):

* **admission control** — ``max_queue`` bounds the waiting line;
  ``submit`` raises :class:`~.scheduler.EngineOverloaded` (with a
  queue-depth hint) once the high watermark is hit, reopening at the
  low watermark (scheduler.py documents the shed policies);
* **deadlines** — ``submit(..., ttl=)`` / ``deadline=`` attaches a TTL
  checked at admission and once per iteration; an expired request
  frees its KV slot immediately mid-flight and finishes with
  ``finish_reason="deadline"`` carrying its partial tokens;
* **cancellation** — ``cancel(rid)`` removes a queued request or
  retires a running one mid-flight (``finish_reason="cancelled"``,
  partial tokens, slot freed on the spot);
* **decode watchdog** — a slot whose logits go non-finite (poisoned
  KV, overflowed activation) is QUARANTINED: retired with
  ``finish_reason="error"``, its slot reclaimed, the other slots'
  token streams untouched — the engine loop survives the fault the
  way the training path survives a NaN batch.  A RAISING jitted step
  cannot be attributed to one slot, so it retires everything in
  flight with "error" and keeps the engine alive for new work.
  ``watchdog=False`` builds the unprotected twin the chaos bench
  wedges for contrast;
* **slot-leak reconcile** — any cache slot owned by nobody (a leak,
  however induced) is swept back to the free list each iteration;
* **consumer protection** — a stream callback that raises is detached
  (the request keeps decoding, tokens land in ``result()``); with
  ``stream_stall_timeout`` set, a callback that stalls longer than the
  bound is detached too, so one stuck client can't hold the whole
  batch hostage more than once.

Because every call sees identical shapes, XLA compiles each program
once — and the compiled pair is SHARED across engine instances with the
same (model, sampling) signature, so twins/rebuilds reuse the same
executable (no recompile, and bitwise-identical token streams across
engines — XLA:CPU recompiles of the same program are not bit-stable).
``trace_counts`` exposes the shared retrace counters; the compile-once
test pins them at 1 after warmup.

The scheduler (scheduler.py) interleaves admission-prefill with decode
at iteration granularity, and the slot pool (kv_cache.py) recycles a
retired request's slot on the next iteration.  Per-request TTFT / TPOT /
queue-wait land in ``records`` as plain dicts; summarize with
``hetu_tpu.metrics.request_latency_summary``.

Usage::

    engine = InferenceEngine(ex, model, n_slots=8, max_len=256,
                             max_queue=64)
    outs = engine.generate_many(prompts, max_new=64)      # batch API
    h = engine.submit(prompt, max_new=64, ttl=2.0,
                      stream=lambda tok, req: print(tok)) # callback API
    engine.cancel(h.rid)                                  # mid-flight
    for tok in engine.stream(prompt, max_new=64):         # generator API
        ...
"""

from __future__ import annotations

import time
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from .. import telemetry as _telemetry
from ..models._decode_common import (make_picker, make_slot_picker,
                                     param_prefix, pad_prompts)
from . import sharding as _shd
from .adapters import adapter_for
from .kv_cache import (PagedKVCache, SlotKVCache, ceil_div, gather_pages,
                       scatter_rows)
from .scheduler import Request, Scheduler


def _p2(n):
    """Next power of two >= n (the prefill bucket rounding)."""
    return 1 << max(0, (int(n) - 1).bit_length())


class InferenceEngine:
    """Continuous-batching generation over a slot-pooled KV cache.

    ``gang=True`` degrades scheduling to static batching (admit only
    when every slot is free) — the serve bench's baseline twin; the
    numerics and jitted programs are identical, only admission differs.
    ``watchdog=False`` disables every host-side protection (quarantine,
    exception containment, leak reconcile) — the chaos bench's
    unprotected twin; the jitted programs are still identical.
    """

    def __init__(self, executor, model, n_slots=4, max_len=128,
                 max_prompt_len=None, prefill_budget=2, eos_id=None,
                 temperature=0.0, top_k=0, seed=0, name=None,
                 gang=False, max_queue=None, low_watermark=None,
                 shed_policy="reject_newest", watchdog=True,
                 stream_stall_timeout=None, clock=None, instance=None,
                 latency_buckets=None, device=None, paged=False,
                 page_len=16, n_pages=None, prefill_token_budget=None,
                 mesh=None, spec_k=0, draft=None, draft_layers=None,
                 spec_min_accept=None, spec_probe_every=32,
                 shared_params=None, prefix_cache=None, kv_dtype=None,
                 gather_dtype=None):
        # shared_params (fleet multi-replica-per-chip): a param pytree
        # ALREADY placed on this engine's device — replicas pinned to
        # the same chip pass one placed copy instead of re-uploading
        # per engine (the HBM ledger's pool=params books it once)
        self.params = (executor.params if shared_params is None
                       else shared_params)
        self.instance = None if instance is None else str(instance)
        self.device = device
        self.mesh = mesh
        self._tp = 1
        if mesh is not None:
            # tensor-parallel serving (serving/sharding.py): this engine
            # spans every device of a (replica=1, model=tp) mesh; GSPMD
            # inserts the collectives from the shardings threaded through
            # the paged program pair below
            if not paged:
                raise ValueError(
                    "mesh= (tensor-parallel serving) requires paged=True "
                    "— the sharded executables are the paged pair")
            if device is not None:
                raise ValueError(
                    "pass device= (single-chip pinning) or mesh= "
                    "(tensor-parallel), not both")
            self._tp = _shd.mesh_axis_size(mesh)
        self._rep = None if mesh is None else _shd.replicated(mesh)
        if shared_params is not None and mesh is not None:
            raise ValueError(
                "shared_params is the single-chip replica-sharing path; "
                "mesh engines own mesh-placed params (see _shd.shard_params)")
        if device is not None and shared_params is None:
            # fleet replica pinning: park THIS engine's params + cache on
            # one device so N replicas split the chips instead of
            # contending for device 0 (jit follows the operands' device)
            self.params = jax.device_put(self.params, device)
        # -- quantized serving plane (ops/quant.py) -----------------------
        # kv_dtype quantizes the paged pool at rest; gather_dtype
        # quantizes the TP all-gathers.  Both default off, and OFF means
        # bitwise-identical programs to an engine built before these
        # knobs existed (the program key only grows a component when one
        # is set, so default engines keep sharing the same executables).
        self._kv_dtype = None if kv_dtype is None else str(kv_dtype)
        if self._kv_dtype is not None and not paged:
            raise ValueError(
                "kv_dtype (quantized KV pages) requires paged=True — "
                "the dense slot pool has no per-page scale layout")
        self._gather_dtype = (None if gather_dtype is None
                              else str(gather_dtype))
        if self._gather_dtype is not None and mesh is None:
            raise ValueError(
                "gather_dtype (quantized TP gathers) requires mesh= — "
                "a single-chip engine has no cross-shard gathers")
        name = name or param_prefix(
            executor, "_embed_table"
            if hasattr(model.config, "rope_theta") else "_wte_table")
        self.adapter = adapter_for(model, name, mesh=mesh,
                                   gather_dtype=self._gather_dtype)
        if mesh is not None:
            _shd.validate_tp(self.adapter, self._tp)
            # every mesh engine owns a mesh-placed copy of the params —
            # fleet replicas on disjoint sub-meshes must not share one
            self.params = _shd.shard_params(mesh, self.adapter,
                                            self.params)
        cap = self.adapter.position_cap
        if cap is not None and max_len > cap:
            raise ValueError(
                f"max_len={max_len} exceeds the model's learned-position "
                f"table ({cap}); build the model with a longer seq_len")
        self.max_len = int(max_len)
        self.max_prompt_len = int(max_prompt_len or max(1, max_len // 2))
        if self.max_prompt_len > self.max_len:
            raise ValueError(
                f"max_prompt_len={self.max_prompt_len} > max_len="
                f"{self.max_len}")
        emb = self.params[self.adapter.embed_param]
        self._paged = bool(paged)
        if self._paged:
            meshkw = ({} if mesh is None else
                      dict(shards=self._tp,
                           put_sharding=_shd.replicated(mesh)))
            self.cache = PagedKVCache(
                n_slots, self.adapter.layers, self.adapter.kv_heads,
                page_len, self.adapter.head_dim, max_len=self.max_len,
                n_pages=n_pages, dtype=emb.dtype,
                kv_dtype=self._kv_dtype,
                label=self.instance or f"{name}:{id(self):x}", **meshkw)
        else:
            self.cache = SlotKVCache(
                n_slots, self.adapter.layers, self.adapter.kv_heads,
                self.max_len, self.adapter.head_dim, dtype=emb.dtype)
        if device is not None:
            self.cache.k = jax.device_put(self.cache.k, device)
            self.cache.v = jax.device_put(self.cache.v, device)
        elif mesh is not None:
            # the page pool splits kv_heads / tp per chip — the dominant
            # serving HBM saving the mesh buys (pages/slots replicated
            # host-side, so the allocator and block tables are untouched)
            kvsh = _shd.kv_sharding(mesh)
            self.cache.k = jax.device_put(self.cache.k, kvsh)
            self.cache.v = jax.device_put(self.cache.v, kvsh)
        if prefill_token_budget is not None:
            prefill_token_budget = int(prefill_token_budget)
            if prefill_token_budget < 1:
                raise ValueError(
                    f"prefill_token_budget must be >= 1, got "
                    f"{prefill_token_budget}")
            if not self._paged:
                raise ValueError(
                    "prefill_token_budget requires paged=True (the slot "
                    "engine prefills whole prompts)")
        self.prefill_token_budget = prefill_token_budget
        # -- speculative decoding (serving/speculative.py) ----------------
        spec_k = int(spec_k)
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if spec_k and not self._paged:
            raise ValueError(
                "spec_k (speculative decoding) requires paged=True — the "
                "verify program is the paged step widened to a window")
        self._spec_k = spec_k
        self._draft = None           # ModelDraft instance (or None)
        self._draft_layers = 0       # SelfDraft depth (0 = model draft)
        if spec_k:
            from . import speculative as _spec
            if draft is None:
                draft = _spec.SelfDraft(draft_layers)
            elif callable(draft) and not hasattr(draft, "kind"):
                draft = draft()      # factory: each replica gets its own
            if draft.kind == "self":
                dl = draft.layers
                if dl is None:
                    dl = (int(draft_layers) if draft_layers is not None
                          else max(1, self.adapter.layers // 2))
                if not 1 <= dl <= self.adapter.layers:
                    raise ValueError(
                        f"draft_layers={dl} outside [1, "
                        f"{self.adapter.layers}]")
                self._draft_layers = int(dl)
            else:
                if mesh is not None:
                    raise ValueError(
                        "ModelDraft is single-chip only; mesh engines "
                        "use the truncated-layer SelfDraft")
                self._draft = draft
        # adaptive gate: fall back to plain decode when the accepted-
        # tokens-per-iteration EWMA sags below spec_min_accept (None =
        # always speculate), re-probing every spec_probe_every plain
        # iterations so recovered acceptance re-enables speculation
        self._spec_min_accept = (None if spec_min_accept is None
                                 else float(spec_min_accept))
        self._spec_probe_every = max(1, int(spec_probe_every))
        self._spec_accept_ewma = float(spec_k + 1)
        self._spec_since_probe = 0
        # -- prefix caching (serving/prefix_cache.py) ---------------------
        self.prefix_cache = None
        if prefix_cache:
            if not self._paged:
                raise ValueError("prefix_cache requires paged=True — a "
                                 "shared prefix is shared PAGES")
            if prefix_cache is True:
                from .prefix_cache import PrefixCache
                self.prefix_cache = PrefixCache(self.cache)
            else:
                if prefix_cache.pool is not self.cache:
                    raise ValueError(
                        "prefix_cache is bound to a different page pool")
                self.prefix_cache = prefix_cache
        # paged prefill batching: lanes per call (B bucket cap) and the
        # chunk-length cap (C bucket cap = the prompt bucket)
        self._lane_cap = min(8, _p2(n_slots))
        self._chunk_cap = _p2(self.max_prompt_len)
        self._prefilling = {}      # slot -> {"req", "start"} mid-chunk
        self._prefill_order = []   # admission order of those slots
        self.scheduler = Scheduler(self.cache,
                                   prefill_budget=prefill_budget,
                                   gang=gang, max_queue=max_queue,
                                   low_watermark=low_watermark,
                                   shed_policy=shed_policy,
                                   rid_prefix=self.instance,
                                   lookahead=spec_k)
        if self.prefix_cache is not None:
            self.scheduler.prefix_lookup = self.prefix_cache.lookup
        self.eos_id = eos_id
        self.watchdog = bool(watchdog)
        self.stream_stall_timeout = (
            None if stream_stall_timeout is None
            else float(stream_stall_timeout))
        self._clock = clock if clock is not None else time.perf_counter
        self._sampling = (float(temperature), int(top_k))
        self._pick = make_picker(temperature, top_k)
        self._key = jax.random.key(seed)
        self._default_seed = int(seed)
        self._last_tokens = np.zeros(n_slots, np.int32)
        # per-slot sampling operands (paged engines thread these through
        # the programs; engine defaults unless submit() overrides)
        self._temps = np.full(n_slots, self._sampling[0], np.float32)
        self._topks = np.full(n_slots, self._sampling[1], np.int32)
        self._seeds = np.full(n_slots, self._default_seed, np.int32)
        # cached device copies of the sampling operands (dropped on
        # admission, the only writer) and of the last active-lane mask:
        # both change at request boundaries but are decode operands
        # EVERY step, and per-step upload dispatch dwarfs the compiled
        # step itself at serving batch sizes
        self._dev_sampling = None
        self._dev_active = (None, None)
        # per-request latency records + per-iteration occupancy log
        # (the per-request API; the registry mirrors below are the LIVE
        # surface — same numbers, scrapeable mid-run via /metrics)
        self.records = []
        self.occupancy = []
        self.decode_steps = 0
        self.prefills = 0
        self.prefill_chunks = 0
        self.peak_active = 0
        self.peak_live_tokens = 0
        self.cancellations = 0
        self.expirations = 0
        self.watchdog_trips = 0
        self.slot_leaks_reclaimed = 0
        self.streams_detached = 0
        self.replayed_tokens = 0
        self.migrated_in = 0       # streams adopted from a sibling's pages
        self.migrated_out = 0      # streams released to a sibling post-ack
        self.spec_steps = 0        # speculative iterations dispatched
        self.spec_proposed = 0     # draft-origin window candidates
        self.spec_accepted = 0     # of those, accepted by verify
        mode = "gang" if gang else "continuous"
        reg = _telemetry.get_registry()
        # per-deployment histogram bucket overrides: real TPU TTFT/TPOT
        # shapes may not fit the default 100us..10s ladder (ROADMAP
        # carry-over).  The registry caches instruments by NAME and
        # rejects a bucket mismatch, so every engine in one process must
        # agree on the ladder — pass the same latency_buckets to each
        # (EngineFleet threads one value through all replicas).
        hkw = ({} if latency_buckets is None
               else {"buckets": tuple(latency_buckets)})

        def _m(kind, name, help, **kw):
            return getattr(reg, kind)(name, help, labels=("scheduler",),
                                      **kw).labels(scheduler=mode)

        self._m_occ = _m("gauge", "hetu_serving_slot_occupancy",
                         "Active-slot fraction of the last decode "
                         "iteration")
        self._m_tokens = _m("counter", "hetu_serving_tokens_total",
                            "Generated tokens emitted")
        self._m_prefill_iters = _m(
            "counter", "hetu_serving_prefill_total",
            "Prompt prefills run (admissions)")
        self._m_decode_iters = _m(
            "counter", "hetu_serving_decode_iterations_total",
            "Slot-batched decode iterations run")
        self._m_finished = _m("counter", "hetu_serving_requests_total",
                              "Requests retired (any finish_reason)")
        self._m_cancelled = _m(
            "counter", "hetu_serving_cancellations_total",
            "Requests cancelled via engine.cancel (queued or running)")
        self._m_expired = _m(
            "counter", "hetu_serving_deadline_expired_total",
            "Requests retired because their deadline passed")
        self._m_watchdog = _m(
            "counter", "hetu_serving_watchdog_trips_total",
            "Decode watchdog quarantines (non-finite logits or a "
            "raising step)")
        self._m_leaks = _m(
            "counter", "hetu_serving_slot_leaks_reclaimed_total",
            "Orphaned KV slots swept back to the free list")
        self._m_detached = _m(
            "counter", "hetu_serving_streams_detached_total",
            "Stream callbacks detached (raised or stalled past the "
            "bound)")
        self._m_replayed = _m(
            "counter", "hetu_serving_replayed_tokens_total",
            "Tokens teacher-forced during failover replay (rebuilt, "
            "never re-emitted)")
        self._m_migrated_in = _m(
            "counter", "hetu_serving_migrated_in_total",
            "Decode streams adopted mid-flight from a sibling's "
            "exported KV pages")
        self._m_migrated_out = _m(
            "counter", "hetu_serving_migrated_out_total",
            "Decode streams released after a sibling acked adoption "
            "of their KV pages")
        self._m_spec_proposed = _m(
            "counter", "hetu_serving_spec_proposed_total",
            "Draft tokens proposed into speculative verify windows")
        self._m_spec_accepted = _m(
            "counter", "hetu_serving_spec_accepted_total",
            "Draft-proposed tokens the verify step accepted")
        self._m_ttft = _m("histogram", "hetu_serving_ttft_seconds",
                          "Time to first token (arrival -> first emit)",
                          **hkw)
        self._m_tpot = _m("histogram", "hetu_serving_tpot_seconds",
                          "Mean time per output token after the first",
                          **hkw)
        self._m_qwait = _m("histogram", "hetu_serving_queue_wait_seconds",
                           "Arrival -> slot admission wait", **hkw)
        if mesh is not None:
            minst = self.instance or name
            reg.gauge(
                "hetu_mesh_tp_size",
                "Model-axis (tensor-parallel) degree of the engine's "
                "serving mesh",
                labels=("engine",)).labels(engine=minst).set(self._tp)
            reg.gauge(
                "hetu_mesh_kv_per_chip_bytes",
                "Bytes of the sharded KV page pool resident per chip",
                labels=("engine",)).labels(engine=minst).set(
                _shd.per_chip_bytes((self.cache.k, self.cache.v)))
            reg.gauge(
                "hetu_mesh_param_per_chip_bytes",
                "Bytes of the engine's (partially sharded) params "
                "resident per chip",
                labels=("engine",)).labels(engine=minst).set(
                _shd.per_chip_bytes(self.params))
        self._tr = _telemetry.get_tracer()
        self._rt = _telemetry.get_request_trace()
        self._fl = _telemetry.get_flight()
        self._verify_fn = None
        self._draft_fn = None
        self._spec_traces = {}
        self._build()
        if self._spec_k:
            self._build_spec()
            if self._draft is not None:
                self._draft.attach(self)

    # -- jitted programs ---------------------------------------------------
    # ONE compiled (prefill, step) pair per (adapter signature, sampling)
    # in the process, shared across engine instances.  Two reasons:
    # * the gang twin and any engine rebuild reuse the executable
    #   instead of recompiling it (the serve bench builds two engines);
    # * XLA:CPU compilation is not bitwise-reproducible across compiles
    #   of the same program in one process (observed: near-tie argmax
    #   flips between two freshly-built engines on identical inputs,
    #   tier-1 flakes in the serving determinism/twin tests), so "the
    #   twin runs the same programs" must mean the same EXECUTABLE, not
    #   a byte-equivalent recompile.
    # The watchdog sentinel is part of the program for EVERY engine
    # (protected and unprotected alike) for the same reason: the
    # executable must be identical so protection is a host-side choice.
    _PROGRAMS = {}

    def _program_key(self):
        cfg = tuple(sorted((k, repr(v)) for k, v in
                           vars(self.adapter.config).items()))
        # paged and slot programs must NEVER collide in _PROGRAMS (or in
        # the profiler caches keyed off cost_signature): the paged pair
        # has different operand signatures (block tables + sampling
        # vectors) and different cache geometry.  Paged sampling is an
        # OPERAND, so the closure constants drop out of its key; the
        # page geometry takes their place.
        if self._paged:
            sampling = ("operands",)
            geometry = ("paged", self.cache.page_len, self.cache.n_pages,
                        self.cache.max_pages)
            if self.mesh is not None:
                # a mesh engine's executables bake device assignments in
                # via in_shardings; fleet sub-meshes on different device
                # groups (and the single-device twin) must not collide
                geometry = geometry + (
                    ("tp", self._tp) + _shd.device_ids(self.mesh),)
        else:
            sampling = self._sampling
            geometry = ("slot",)
        # quantization components are appended ONLY when the knobs are
        # set: a default f32 engine's key — and therefore its cached
        # executables — is byte-identical to one built before the
        # quantized plane existed (the strictly-opt-in guarantee)
        if self._kv_dtype is not None:
            geometry = geometry + (("kv_dtype", self._kv_dtype),)
        if self._gather_dtype is not None:
            geometry = geometry + (("gather_dtype", self._gather_dtype),)
        return (type(self.adapter).__name__, self.adapter.name, cfg,
                sampling, geometry, jax.default_backend())

    def _build(self):
        if self._paged:
            self._build_paged()
            return
        entry = self._PROGRAMS.get(self._program_key())
        if entry is None:
            adapter, pick = self.adapter, self._pick
            from .. import telemetry as _tel
            retrace = _tel.get_registry().counter(
                "hetu_serving_retraces_total",
                "Times each jitted serving program was traced — >1 "
                "after warmup breaks the compile-once contract",
                labels=("program",))
            traces = {"prefill": 0, "step": 0}

            def prefill(params, k, v, prompt, p_len, slot, key):
                traces["prefill"] += 1     # host-side retrace witness
                retrace.labels(program="prefill").inc()
                logits, kn, vn = adapter.prefill(params, prompt)
                k = jax.lax.dynamic_update_slice(k, kn[None],
                                                 (slot, 0, 0, 0, 0))
                v = jax.lax.dynamic_update_slice(v, vn[None],
                                                 (slot, 0, 0, 0, 0))
                row = jax.lax.dynamic_slice_in_dim(logits, p_len - 1, 1,
                                                   0)
                # watchdog sentinel: finiteness of the row that seeds
                # the request (fuses with the logits reduction)
                ok = jnp.all(jnp.isfinite(row))
                tok = pick(row, key)[0].astype(jnp.int32)
                return k, v, tok, ok

            def step(params, k, v, tokens, positions, active, key):
                traces["step"] += 1        # host-side retrace witness
                retrace.labels(program="step").inc()
                logits, k, v = adapter.decode(params, tokens, positions,
                                              k, v)
                # per-slot watchdog sentinel: a poisoned slot flags ONLY
                # itself (slots attend their own cache rows only), so
                # the host can quarantine it without touching the rest
                slot_ok = jnp.all(jnp.isfinite(logits), axis=-1)
                nxt = pick(logits, key).astype(jnp.int32)
                return k, v, jnp.where(active, nxt, 0), slot_ok

            # donate the cache buffers so the pool is updated in place
            # on accelerator backends (on CPU jax cannot donate; skip
            # the per-call warning)
            donate = () if jax.default_backend() == "cpu" else (1, 2)
            entry = {"prefill": jax.jit(prefill, donate_argnums=donate),
                     "step": jax.jit(step, donate_argnums=donate),
                     "traces": traces}
            self._PROGRAMS[self._program_key()] = entry
        self._prefill_fn = entry["prefill"]
        self._step_fn = entry["step"]
        self._traces = entry["traces"]

    def _build_paged(self):
        """The paged program pair: same math as the slot pair, but both
        programs gather per-slot caches from the page pool through the
        block-table operand, write the new rows back with a scatter
        (inactive/pad lanes routed to sentinel page 0), and sample from
        per-slot operand vectors.  Prefill is batched ``[B, C]`` — one
        jitted callable retracing once per pow2 (B, C) bucket, each
        bucket its own entry in the retrace witness."""
        entry = self._PROGRAMS.get(self._program_key())
        if entry is None:
            adapter = self.adapter
            pick = make_slot_picker()
            from .. import telemetry as _tel
            retrace = _tel.get_registry().counter(
                "hetu_serving_retraces_total",
                "Times each jitted serving program was traced — >1 "
                "after warmup breaks the compile-once contract",
                labels=("program",))
            traces = {"step": 0}

            def prefill(params, k, v, prompts, p_lens, starts,
                        chunk_lens, tables, temps, top_ks, seeds):
                bb, cb = prompts.shape
                tag = f"prefill[{bb}x{cb}]"   # retrace witness per bucket
                traces[tag] = traces.get(tag, 0) + 1
                retrace.labels(program=tag).inc()
                nl, nkv, nd = k.shape[1], k.shape[2], k.shape[4]
                page_len, mp = k.shape[3], tables.shape[1]
                kc = gather_pages(k, tables)
                vc = gather_pages(v, tables)
                # pad the gathered time axis by C so the in-block write
                # at ``start`` never clamps (dynamic_update_slice CLAMPS
                # an out-of-range start, which would silently shift a
                # pad lane's garbage onto valid rows)
                pad = ((0, 0), (0, 0), (0, 0), (0, cb), (0, 0))
                kc, vc = jnp.pad(kc, pad), jnp.pad(vc, pad)
                logits, kc, vc = adapter.prefill_chunk(
                    params, prompts, starts, kc, vc)
                # write-back: chunk rows -> (page, offset); rows past the
                # lane's true chunk length go to sentinel page 0
                rows = starts[:, None] + jnp.arange(cb)[None, :]
                valid = jnp.arange(cb)[None, :] < chunk_lens[:, None]
                pidx = jnp.clip(rows // page_len, 0, mp - 1)
                pages = jnp.where(
                    valid, jnp.take_along_axis(tables, pidx, axis=1), 0)
                offs = rows % page_len
                rix = jnp.clip(rows, 0,
                               kc.shape[3] - 1)[:, None, None, :, None]
                krows = jnp.take_along_axis(kc, rix, axis=3)
                vrows = jnp.take_along_axis(vc, rix, axis=3)
                n = bb * cb
                k = scatter_rows(
                    k, pages.reshape(n), offs.reshape(n),
                    krows.transpose(0, 3, 1, 2, 4).reshape(n, nl, nkv, nd))
                v = scatter_rows(
                    v, pages.reshape(n), offs.reshape(n),
                    vrows.transpose(0, 3, 1, 2, 4).reshape(n, nl, nkv, nd))
                last = jnp.clip(chunk_lens - 1, 0, cb - 1)
                lrow = jnp.take_along_axis(
                    logits, last[:, None, None], axis=1)[:, 0]   # [B, V]
                ok = jnp.all(jnp.isfinite(lrow), axis=-1)
                # sampling key folds the request's consumed count: the
                # first generated token is token p_len of the stream
                tok = pick(lrow, temps, top_ks, seeds,
                           p_lens).astype(jnp.int32)
                return k, v, tok, ok

            def step(params, k, v, tokens, positions, tables, active,
                     temps, top_ks, seeds):
                traces["step"] += 1        # host-side retrace witness
                retrace.labels(program="step").inc()
                page_len, mp = k.shape[3], tables.shape[1]
                kc = gather_pages(k, tables)
                vc = gather_pages(v, tables)
                logits, kc, vc = adapter.decode(params, tokens,
                                                positions, kc, vc)
                slot_ok = jnp.all(jnp.isfinite(logits), axis=-1)
                nxt = pick(logits, temps, top_ks, seeds,
                           positions + 1).astype(jnp.int32)
                pidx = jnp.clip(positions // page_len, 0, mp - 1)
                pages = jnp.where(
                    active,
                    jnp.take_along_axis(tables, pidx[:, None],
                                        axis=1)[:, 0],
                    0)
                offs = positions % page_len
                rix = jnp.clip(positions, 0,
                               kc.shape[3] - 1)[:, None, None, None, None]
                krow = jnp.take_along_axis(kc, rix, axis=3)[:, :, :, 0]
                vrow = jnp.take_along_axis(vc, rix, axis=3)[:, :, :, 0]
                k = scatter_rows(k, pages, offs, krow)
                v = scatter_rows(v, pages, offs, vrow)
                return k, v, jnp.where(active, nxt, 0), slot_ok

            donate = () if jax.default_backend() == "cpu" else (1, 2)
            pjkw, sjkw = {}, {}
            if self.mesh is not None:
                # thread NamedShardings through both programs: params by
                # their layout map, the page pool on kv_heads, every
                # host-built operand (and every token/sentinel output)
                # replicated — XLA inserts the all-gathers at the
                # gather= hook points in the block math
                psh = _shd.param_shardings(self.mesh, adapter,
                                           self.params)
                kvsh = _shd.kv_sharding(self.mesh)
                rep = _shd.replicated(self.mesh)
                pjkw = dict(in_shardings=(psh, kvsh, kvsh) + (rep,) * 8,
                            out_shardings=(kvsh, kvsh, rep, rep))
                sjkw = dict(in_shardings=(psh, kvsh, kvsh) + (rep,) * 7,
                            out_shardings=(kvsh, kvsh, rep, rep))
            entry = {"prefill": jax.jit(prefill, donate_argnums=donate,
                                        **pjkw),
                     "step": jax.jit(step, donate_argnums=donate,
                                     **sjkw),
                     "traces": traces}
            self._PROGRAMS[self._program_key()] = entry
        self._prefill_fn = entry["prefill"]
        self._step_fn = entry["step"]
        self._traces = entry["traces"]

    def _build_spec(self):
        """The speculative program pair, cached under the paged program
        key EXTENDED with the window geometry.  Extending (never
        changing) the key keeps this engine's prefill and one-token
        step as the SAME executables its non-speculative twin runs —
        the bitwise-parity and equal-footing contracts — while verify/
        draft are shared across engines with the same signature."""
        from . import speculative as _spec
        key = self._program_key() + (
            ("spec", self._spec_k, self._draft_layers),)
        entry = self._PROGRAMS.get(key)
        if entry is None:
            adapter = self.adapter
            pick = make_slot_picker()
            from .. import telemetry as _tel
            retrace = _tel.get_registry().counter(
                "hetu_serving_retraces_total",
                "Times each jitted serving program was traced — >1 "
                "after warmup breaks the compile-once contract",
                labels=("program",))
            traces = {"verify": 0}
            verify_core = _spec.make_verify_fn(adapter, pick,
                                               self._spec_k + 1)

            def verify(*a):
                traces["verify"] += 1      # host-side retrace witness
                retrace.labels(program="verify").inc()
                return verify_core(*a)

            draft_jit = None
            if self._draft_layers:
                traces["draft"] = 0
                draft_core = _spec.make_self_draft_fn(
                    adapter, pick, self._spec_k, self._draft_layers)

                def draft(*a):
                    traces["draft"] += 1   # host-side retrace witness
                    retrace.labels(program="draft").inc()
                    return draft_core(*a)

            donate = () if jax.default_backend() == "cpu" else (1, 2)
            vjkw, djkw = {}, {}
            if self.mesh is not None:
                psh = _shd.param_shardings(self.mesh, adapter,
                                           self.params)
                kvsh = _shd.kv_sharding(self.mesh)
                rep = _shd.replicated(self.mesh)
                vjkw = dict(in_shardings=(psh, kvsh, kvsh) + (rep,) * 7,
                            out_shardings=(kvsh, kvsh, rep, rep))
                djkw = dict(in_shardings=(psh, kvsh, kvsh) + (rep,) * 6,
                            out_shardings=rep)
            if self._draft_layers:
                # NO donation: the draft is carry-only over the pool
                draft_jit = jax.jit(draft, **djkw)
            entry = {"verify": jax.jit(verify, donate_argnums=donate,
                                       **vjkw),
                     "draft": draft_jit,
                     "traces": traces}
            self._PROGRAMS[key] = entry
        self._verify_fn = entry["verify"]
        self._draft_fn = entry["draft"]
        self._spec_traces = entry["traces"]

    @property
    def trace_counts(self):
        """{'prefill': n, 'step': n, ...} — times each (shared) program
        was traced; 1 after warmup means every engine with this
        signature runs the same executable at the same shapes.
        Speculative engines add their verify/draft witnesses (and a
        ModelDraft its prefill/step pair) to the same dict."""
        out = dict(self._traces)
        out.update(self._spec_traces)
        if self._draft is not None:
            out.update(self._draft.trace_counts)
        return out

    def _dev_put(self, host_array):
        """Upload a host-built operand.  Mesh engines place it
        replicated over their devices ONCE, so the cached copies below
        aren't resharded by every jit dispatch."""
        if self.mesh is not None:
            return jax.device_put(host_array, self._rep)
        return jnp.asarray(host_array)

    # AOT (prefill, decode) executables keyed by cost_signature():
    # engines sharing a signature share exact shapes, so the compiled
    # analysis pair is identical and a raw cost_programs() call is
    # retrace-free after the first per signature
    _COST_PROGRAMS = {}

    def cost_programs(self, force=False):
        """AOT-lower + compile the (prefill, decode) pair at this
        engine's exact serving shapes and return ``{"prefill":
        compiled, "decode": compiled}`` for the profiling layer
        (``telemetry.profiling.ProgramProfiler.capture``).

        Pure analysis — nothing executes and no engine state changes.
        Results are cached per :meth:`cost_signature` (like the shared
        serving programs), so only the FIRST call per signature pays
        the re-lower/re-trace; repeat calls — and
        :meth:`capture_cost_profiles` misses — stay retrace-flat even
        inside a compile-once assertion window.  ``force=True``
        rebuilds (and refreshes the cache) unconditionally."""
        sig = self.cost_signature()
        if not force:
            cached = self._COST_PROGRAMS.get(sig)
            if cached is not None:
                return dict(cached)

        def ab(x):
            return jax.ShapeDtypeStruct(jnp.shape(x), x.dtype)

        params = jax.tree_util.tree_map(ab, self.params)
        # quantized pools are pytrees (codes + scales): abstract per leaf
        k = jax.tree_util.tree_map(ab, self.cache.k)
        v = jax.tree_util.tree_map(ab, self.cache.v)
        key = ab(self._key)
        n = self.cache.n_slots
        lane = jax.ShapeDtypeStruct((n,), jnp.int32)
        active = jax.ShapeDtypeStruct((n,), jnp.bool_)
        if self._paged:
            # analysis shapes: a full-lane [B=lane_cap, C=chunk_cap]
            # prefill bucket and the (only) decode signature
            b = self._lane_cap
            mp = self.cache.max_pages
            prompts = jax.ShapeDtypeStruct((b, self._chunk_cap),
                                           jnp.int32)
            blane = jax.ShapeDtypeStruct((b,), jnp.int32)
            bf32 = jax.ShapeDtypeStruct((b,), jnp.float32)
            btab = jax.ShapeDtypeStruct((b, mp), jnp.int32)
            tab = jax.ShapeDtypeStruct((n, mp), jnp.int32)
            f32 = jax.ShapeDtypeStruct((n,), jnp.float32)
            progs = {"prefill": self._prefill_fn.lower(
                         params, k, v, prompts, blane, blane, blane,
                         btab, bf32, blane, blane).compile(),
                     "decode": self._step_fn.lower(
                         params, k, v, lane, lane, tab, active, f32,
                         lane, lane).compile()}
        else:
            prompt = jax.ShapeDtypeStruct((1, self.max_prompt_len),
                                          jnp.int32)
            scalar = jax.ShapeDtypeStruct((), jnp.int32)
            progs = {"prefill": self._prefill_fn.lower(
                         params, k, v, prompt, scalar, scalar,
                         key).compile(),
                     "decode": self._step_fn.lower(
                         params, k, v, lane, lane, active, key).compile()}
        self._COST_PROGRAMS[sig] = dict(progs)
        return progs

    def cost_signature(self):
        """Stable identity of the compiled (prefill, decode) pair at
        this engine's serving shapes — the profiler's capture-cache
        key.  Same adapter/config/sampling/backend (the shared program
        key) plus the same slot geometry means the same executables,
        so a cached cost/memory capture is exact, not approximate."""
        return repr((self._program_key(), self.cache.n_slots,
                     self.max_len, self.max_prompt_len))

    def capture_cost_profiles(self, profiler, kind="serve", prefix=None):
        """Capture cost/memory for both serving programs through
        ``profiler``'s signature cache (profile names
        ``{prefix}_prefill`` / ``{prefix}_decode``; the prefix defaults
        to the adapter name, matching ``bench.py --profile``).  Only a
        cache MISS builds the AOT programs — :meth:`cost_programs` runs
        at most once per call and not at all when both signatures hit,
        so calling this every controller tick never re-traces."""
        prefix = self.adapter.name if prefix is None else str(prefix)
        sig = self.cost_signature()
        progs = {}

        def deferred(which):
            def build():
                if not progs:
                    progs.update(self.cost_programs())
                return progs[which]
            return build

        return {which: profiler.capture(
                    f"{prefix}_{which}", deferred(which), kind=kind,
                    signature=f"{sig}:{which}")
                for which in ("prefill", "decode")}

    def close(self):
        """Release engine-owned HBM-ledger accounting (the KV slot
        pool, a ModelDraft's cache, the prefix cache's retained pages).
        Idempotent; scheduler/stats state stays readable."""
        if self._draft is not None:
            self._draft.close()
        if self.prefix_cache is not None:
            self.prefix_cache.close()
        self.cache.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- request API -------------------------------------------------------
    def submit(self, prompt, max_new, stream=None, eos_id=None,
               arrival=None, deadline=None, ttl=None, replay=None,
               rid=None, temperature=None, top_k=None, seed=None):
        """Queue one generation request; returns its Request handle.
        ``stream(token, request)`` is called per generated token.
        ``ttl`` (seconds from now) or ``deadline`` (absolute, on the
        engine's monotonic clock) bounds the request's lifetime: past
        it, the request finishes with ``finish_reason="deadline"`` and
        whatever tokens it produced.  ``replay=`` (fleet failover)
        teacher-forces a previous attempt's tokens to rebuild the KV
        state without re-emitting them, and ``rid=`` keeps the failed
        attempt's cluster-level id.  ``temperature=`` / ``top_k=`` /
        ``seed=`` override the engine defaults for THIS request (paged
        engines only — per-slot sampling is a decode operand there, a
        compile-time constant on the slot engine).  Raises
        :class:`~.scheduler.EngineOverloaded` when the bounded queue
        refuses admission."""
        if not self._paged and (temperature is not None
                                or top_k is not None or seed is not None):
            raise ValueError(
                "per-request sampling (temperature/top_k/seed) requires "
                "a paged engine (paged=True); the slot engine bakes "
                "sampling into the compiled program")
        if temperature is not None and float(temperature) < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if top_k is not None and int(top_k) < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size > self.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.size} exceeds max_prompt_len="
                f"{self.max_prompt_len}")
        max_new = int(max_new)
        if prompt.size + max_new > self.max_len - self._spec_k:
            # the spec_k headroom is the verify window's worst-case
            # overhang: admission reserves it so the window can never
            # scatter past a slot's pages mid-flight (admission stays
            # the only refusal point)
            spec = (f" - spec_k={self._spec_k}" if self._spec_k else "")
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({max_new}) exceeds "
                f"max_len={self.max_len}{spec}")
        now = self._now()
        if ttl is not None:
            if deadline is not None:
                raise ValueError("pass ttl= or deadline=, not both")
            if ttl <= 0:
                raise ValueError(f"ttl must be > 0, got {ttl}")
            deadline = now + float(ttl)
        req = Request(prompt, max_new,
                      arrival=now if arrival is None else arrival,
                      stream=stream,
                      eos_id=self.eos_id if eos_id is None else eos_id,
                      deadline=deadline, replay=replay, rid=rid,
                      temperature=temperature, top_k=top_k, seed=seed)
        try:
            self.scheduler.submit(req, now=now)
        finally:
            # drop_expired_first may have shed dead seats even when the
            # newcomer was still refused — their records must not be lost
            for shed in self.scheduler.drain_shed():
                self.expirations += 1
                self._m_expired.inc()
                self._finalize_unadmitted(shed, "deadline", now)
        return req

    def cancel(self, rid):
        """Cancel the live request with this rid: a queued request
        leaves the queue, a running one is retired MID-FLIGHT (slot
        freed immediately).  Either way it finishes with
        ``finish_reason="cancelled"`` and its partial tokens in
        ``result()``.  Returns True if a live request was cancelled,
        False if the rid is unknown or already finished."""
        req = self.scheduler.find(rid)
        if req is None:
            return False
        now = self._now()
        req.cancel_requested = True
        if req.slot is not None:
            self._finalize_active(req, "cancelled", now)
        else:
            self.scheduler.remove_queued(req)
            self._finalize_unadmitted(req, "cancelled", now)
        self.cancellations += 1
        self._m_cancelled.inc()
        return True

    def prefix_hit_tokens(self, prompt):
        """Tokens of ``prompt`` an interned prefix would cover at
        admission (0 without a prefix cache) — the fleet's routing
        tie-break toward the replica holding the warmest prefix."""
        if self.prefix_cache is None:
            return 0
        return self.prefix_cache.hit_tokens(
            np.asarray(prompt, np.int32).reshape(-1))

    @property
    def spec_accepted_per_step(self):
        """Measured accepted-tokens-per-verify-step EWMA (None when not
        speculating) — the SLO cost model's per-token decode divisor."""
        return self._spec_accept_ewma if self._spec_k else None

    def _now(self):
        return self._clock()

    def _absorb_replay(self, req, tok):
        """Book a teacher-forced replay token: it lands in ``tokens``
        (so eos/max_new accounting and ``result()`` see the full stream)
        but is never re-emitted — the client already received it from
        the previous attempt."""
        req.tokens.append(int(tok))
        self.replayed_tokens += 1
        self._m_replayed.inc()

    def _emit(self, req, tok, now):
        req.tokens.append(int(tok))
        self._m_tokens.inc()
        if req.t_first is None:
            req.t_first = now
        if req.stream is not None:
            t0 = self._clock()
            try:
                req.stream(int(tok), req)
            except Exception as e:
                if not self.watchdog:
                    raise
                # a raising consumer is the CLIENT's fault — detach it
                # and keep decoding; the tokens still land in result()
                req.stream = None
                self.streams_detached += 1
                self._m_detached.inc()
                warnings.warn(
                    f"stream callback for request {req.rid} raised "
                    f"{type(e).__name__}: {e} — detached (decode "
                    "continues, tokens land in result())")
                return
            if (self.stream_stall_timeout is not None
                    and self._clock() - t0 > self.stream_stall_timeout):
                # one stalled delivery already cost a full iteration for
                # every slot; don't let it happen again
                req.stream = None
                self.streams_detached += 1
                self._m_detached.inc()
                warnings.warn(
                    f"stream callback for request {req.rid} stalled "
                    f"longer than {self.stream_stall_timeout}s — "
                    "detached (decode continues)")

    def _record(self, req):
        self.records.append({
            "id": req.rid, "prompt_len": int(req.prompt.size),
            "n_tokens": len(req.tokens),
            "queue_wait": req.queue_wait, "ttft": req.ttft,
            "tpot": req.tpot, "finish_reason": req.finish_reason})
        # timeline: the marker event for HOW the attempt ended, then the
        # terminal itself ("failover" is attempt-terminal only — the
        # fleet continues the same rid on a sibling, so the timeline
        # stays live past a "harvested"+finish(failover) pair)
        reason = req.finish_reason
        if reason == "deadline":
            self._rt.event(req.rid, "expired", engine=self.instance)
        elif reason == "cancelled":
            self._rt.event(req.rid, "cancelled", engine=self.instance)
        elif reason == "failover":
            self._rt.event(req.rid, "harvested", engine=self.instance)
        self._rt.event(req.rid, "finish", engine=self.instance,
                       reason=reason, tokens=len(req.tokens))
        # registry mirror of the record: the same latencies land in
        # scrape-able histograms without changing records' shape
        self._m_finished.inc()
        for m, v in ((self._m_qwait, req.queue_wait),
                     (self._m_ttft, req.ttft),
                     (self._m_tpot, req.tpot)):
            if v is not None:
                m.observe(v)

    def _finalize_active(self, req, reason, now):
        """Retire a RUNNING request (slot freed immediately).  A
        request retired mid-chunked-prefill (cancel/expire/harvest)
        also leaves the in-progress prefill registry."""
        if req.slot is not None and req.slot in self._prefilling:
            self._prefilling.pop(req.slot, None)
            if req.slot in self._prefill_order:
                self._prefill_order.remove(req.slot)
        if self._draft is not None and req.slot is not None:
            self._draft.release(req.slot)
        req.t_done = now
        self.scheduler.retire(req, reason)
        self._record(req)

    def _finalize_unadmitted(self, req, reason, now):
        """Finish a request that never held a slot (expired or
        cancelled while queued): zero tokens, ttft None."""
        req.t_done = now
        req.finished = True
        req.finish_reason = reason
        self._record(req)

    def _maybe_retire(self, req, tok, now):
        done_eos = req.eos_id is not None and int(tok) == req.eos_id
        if done_eos or len(req.tokens) >= req.max_new:
            self._finalize_active(req, "eos" if done_eos else "max_new",
                                  now)

    def _expire(self, now):
        """Deadline sweep: queued requests past their deadline finish
        without ever taking a slot; running ones retire mid-flight with
        their partial tokens."""
        for req in self.scheduler.take_expired(now):
            self.expirations += 1
            self._m_expired.inc()
            self._finalize_unadmitted(req, "deadline", now)
        expired = [r for r in self.scheduler.running.values()
                   if r.expired(now)]
        for req in expired:
            self.expirations += 1
            self._m_expired.inc()
            self._finalize_active(req, "deadline", now)

    def harvest(self):
        """Remove every live request for fleet failover: running ones
        retire with the attempt-level ``finish_reason="failover"`` (slot
        freed on the spot, so this engine's alloc/free audit stays
        balanced), queued ones leave the queue the same way.  Returns
        the harvested requests, running (admission order) before queued
        (FIFO) — the order a sibling should re-admit them in.  The
        cluster-level request is NOT finished by this: the fleet
        re-submits the same rid elsewhere with ``replay=`` carrying each
        request's tokens-so-far."""
        now = self._now()
        out = []
        for rid in self.scheduler.admitted_order:
            req = next((r for r in self.scheduler.running.values()
                        if r.rid == rid), None)
            if req is not None:
                self._finalize_active(req, "failover", now)
                out.append(req)
        # defensive: any running request not in admitted_order
        for req in list(self.scheduler.running.values()):
            self._finalize_active(req, "failover", now)
            out.append(req)
        while self.scheduler.queue:
            req = self.scheduler.queue.popleft()
            self._finalize_unadmitted(req, "failover", now)
            out.append(req)
        return out

    # -- live KV migration (serving/kv_transfer.py rides these) ------------
    def adopt_request(self, prompt, tokens, pages, position, max_new, *,
                      rid=None, stream=None, eos_id=None, deadline=None,
                      temperature=None, top_k=None, seed=None,
                      arrival=None):
        """Resume a sibling's mid-decode stream from spliced pages.

        ``pages`` are ids from THIS pool's :meth:`~.kv_cache.PagedKVCache.
        import_pages` (one caller-owned reference each); ``tokens`` are
        the stream's already-delivered generated ids (never re-emitted);
        ``position`` is the donor's cached-row count, which for a stream
        with T >= 1 generated tokens is exactly ``prompt + T - 1`` — the
        newest token is a decode operand, not a cache row.  Paged
        sampling keys fold only the per-request seed and the consumed
        count, so the continued stream is BITWISE the uninterrupted one.

        On success the request owns the pages (the caller's reference is
        released here) and decodes on the next iteration.  Returns None
        when admission is refused (no slot/pages — caller keeps its page
        reference and falls back to replay)."""
        if not self._paged:
            raise ValueError("adopt_request requires a paged engine — "
                             "migration moves pages, not slots")
        if self._draft is not None:
            raise ValueError(
                "adopt_request cannot target a ModelDraft engine: the "
                "draft's per-slot state is not part of the wire format "
                "(use replay, or the truncated-layer SelfDraft)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tokens = [int(t) for t in tokens]
        if len(tokens) < 1:
            raise ValueError(
                "adopt_request needs >= 1 generated token (a mid-prefill "
                "stream has no decode state to move — replay it)")
        max_new = int(max_new)
        if len(tokens) >= max_new:
            raise ValueError(
                f"stream already holds {len(tokens)} >= max_new="
                f"{max_new} tokens — nothing left to decode")
        if int(position) != prompt.size + len(tokens) - 1:
            raise ValueError(
                f"position {int(position)} != prompt ({prompt.size}) + "
                f"tokens ({len(tokens)}) - 1 — donor state torn")
        if prompt.size > self.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.size} exceeds max_prompt_len="
                f"{self.max_prompt_len}")
        if prompt.size + max_new > self.max_len - self._spec_k:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({max_new}) exceeds "
                f"max_len={self.max_len}")
        now = self._now()
        slot = self.cache.alloc(
            owner=rid,
            n_tokens=prompt.size + max_new + self.scheduler.lookahead,
            shared=pages)
        if slot is None:
            return None
        # the slot now holds its own reference on every page; dropping
        # the caller's makes them private again (refcount 1) so the
        # next decode write into the partially-filled last page is an
        # in-place write, not a copy-on-write fork
        self.cache.release_pages(pages)
        req = Request(prompt, max_new,
                      arrival=now if arrival is None else arrival,
                      stream=stream,
                      eos_id=self.eos_id if eos_id is None else eos_id,
                      deadline=deadline, rid=rid,
                      temperature=temperature, top_k=top_k, seed=seed)
        if req.rid is None:
            n = next(self.scheduler._ids)
            req.rid = (n if self.scheduler.rid_prefix is None
                       else f"{self.scheduler.rid_prefix}-{n}")
        req.tokens = tokens
        req.prefix_tokens = 0
        req.slot = slot
        req.t_admit = now
        req.t_first = now
        self.cache.positions[slot] = int(position)
        self._last_tokens[slot] = tokens[-1]
        self._temps[slot] = (self._sampling[0] if temperature is None
                             else float(temperature))
        self._topks[slot] = (self._sampling[1] if top_k is None
                             else int(top_k))
        self._seeds[slot] = (self._default_seed if seed is None
                             else int(seed))
        self._dev_sampling = None
        self.scheduler.running[slot] = req
        self.scheduler.admitted_order.append(req.rid)
        self.migrated_in += 1
        self._m_migrated_in.inc()
        self._rt.event(req.rid, "migrated", engine=self.instance,
                       tokens=len(tokens), pages=len(pages))
        return req

    def release_migrated(self, rid):
        """Donor-side ack: a sibling adopted this stream, so retire the
        local attempt with the attempt-level ``finish_reason="failover"``
        (the cluster-level request lives on over there) and free its
        slot and pages NOW — never before the receiver holds its own
        copy.  Returns True if a live request was released."""
        req = self.scheduler.find(rid)
        if req is None:
            return False
        now = self._now()
        if req.slot is not None:
            self._finalize_active(req, "failover", now)
        else:
            self.scheduler.remove_queued(req)
            self._finalize_unadmitted(req, "failover", now)
        self.migrated_out += 1
        self._m_migrated_out.inc()
        return True

    def _quarantine_all(self, reason, now):
        """A fault that cannot be attributed to one slot (the jitted
        step itself raised): retire everything in flight with "error"
        and keep the engine alive for new work."""
        for req in list(self.scheduler.running.values()):
            self._rt.event(req.rid, "watchdog_trip",
                           engine=self.instance, why="step_raise")
            self._finalize_active(req, "error", now)
        self.watchdog_trips += 1
        self._m_watchdog.inc()
        self._fl.incident("watchdog",
                          extra={"engine": self.instance,
                                 "why": reason})
        warnings.warn(
            f"decode watchdog: {reason} — all in-flight requests "
            "retired with finish_reason='error'; engine continues")

    # -- the iteration -----------------------------------------------------
    def _prefill_paged(self):
        """Paged admission/prefill: continue in-flight chunked prefills
        (admission order), admit new requests up to the scheduler's
        count budget AND the per-iteration ``prefill_token_budget``,
        then run ALL lanes as ONE batched ``[B, C]`` prefill call (both
        axes pow2-bucketed).  Lanes whose final chunk lands emit their
        first token; the rest park in ``_prefilling`` and decode
        proceeds around them.  Returns tokens produced."""
        produced = 0
        budget = self.prefill_token_budget
        used = 0
        work = []   # [req, slot, start, chunk_len]
        for slot in list(self._prefill_order):
            if (len(work) >= self._lane_cap
                    or (budget is not None and used >= budget)):
                break
            st = self._prefilling[slot]
            req = st["req"]
            clen = min(int(req.prompt.size) - st["start"],
                       self._chunk_cap)
            if budget is not None:
                clen = min(clen, budget - used)
            if clen <= 0:
                break
            work.append((req, slot, st["start"], clen))
            used += clen
        if (len(work) < self._lane_cap
                and (budget is None or used < budget)):
            tb = None if budget is None else budget - used
            for req, slot in self.scheduler.admit(token_budget=tb):
                req.t_admit = self._now()
                self._rt.event(req.rid, "admitted",
                               engine=self.instance, slot=slot)
                self._rt.event(req.rid, "prefill_start",
                               engine=self.instance, slot=slot,
                               prompt_len=int(req.prompt.size))
                self._temps[slot] = (self._sampling[0]
                                     if req.temperature is None
                                     else req.temperature)
                self._topks[slot] = (self._sampling[1]
                                     if req.top_k is None else req.top_k)
                self._seeds[slot] = (self._default_seed
                                     if req.seed is None else req.seed)
                self._dev_sampling = None
                # prefix-cache hit: the scheduler shared the interned
                # pages into this slot at alloc — prefill starts AFTER
                # them (rows < start read the shared pages via the
                # gathered block table; nothing is recomputed)
                start0 = int(getattr(req, "prefix_tokens", 0))
                if start0:
                    self._rt.event(req.rid, "prefix_hit",
                                   engine=self.instance, slot=slot,
                                   tokens=start0)
                self._prefilling[slot] = {"req": req, "start": start0}
                self._prefill_order.append(slot)
                clen = min(int(req.prompt.size) - start0,
                           self._chunk_cap)
                if budget is not None:
                    clen = min(clen, budget - used)
                if clen > 0 and len(work) < self._lane_cap:
                    work.append((req, slot, start0, clen))
                    used += clen
        if not work:
            return 0
        bb = min(_p2(len(work)), self._lane_cap)
        cb = _p2(max(w[3] for w in work))
        mp = self.cache.max_pages
        prompts = np.zeros((bb, cb), np.int32)
        p_lens = np.ones(bb, np.int32)
        starts = np.zeros(bb, np.int32)
        chunk_lens = np.zeros(bb, np.int32)   # pad lanes: 0 valid rows
        tables = np.zeros((bb, mp), np.int32)
        temps = np.zeros(bb, np.float32)
        topks = np.zeros(bb, np.int32)
        seeds = np.zeros(bb, np.int32)
        for i, (req, slot, start, clen) in enumerate(work):
            prompts[i, :clen] = req.prompt[start:start + clen]
            p_lens[i] = req.prompt.size
            starts[i] = start
            chunk_lens[i] = clen
            tables[i] = self.cache.block_tables[slot]
            temps[i] = self._temps[slot]
            topks[i] = self._topks[slot]
            seeds[i] = self._seeds[slot]
        for req, slot, start, clen in work:
            # CoW discipline: chunk writes start AFTER any shared
            # prefix, so they can only hit privately-held pages.  The
            # guard (on in tests) turns a violation into a loud raise
            # instead of silent cross-request contamination.
            if self.cache.pages_shared:
                self.cache.ensure_writable(slot, start, clen)
            if self.cache.cow_guard:
                self.cache.assert_writable(slot, start, clen)
        try:
            with self._tr.span("serve_prefill"):
                k, v, toks, oks = self._prefill_fn(
                    self.params, self.cache.k, self.cache.v,
                    self._dev_put(prompts), self._dev_put(p_lens),
                    self._dev_put(starts), self._dev_put(chunk_lens),
                    self._dev_put(tables), self._dev_put(temps),
                    self._dev_put(topks), self._dev_put(seeds))
                self.cache.update(k, v)
                toks = np.asarray(toks)
                oks = np.asarray(oks)
        except Exception as e:
            if not self.watchdog:
                raise
            now = self._now()
            self.watchdog_trips += 1
            self._m_watchdog.inc()
            why = (f"batched prefill raised {type(e).__name__}: {e}")
            warnings.warn(f"decode watchdog: {why} — quarantined")
            for req, slot, start, clen in work:
                self._rt.event(req.rid, "watchdog_trip",
                               engine=self.instance,
                               why="prefill_raise")
                self._fl.incident("watchdog", rid=req.rid,
                                  extra={"engine": self.instance,
                                         "why": why})
                self._finalize_active(req, "error", now)
            return 0
        now = self._now()
        for i, (req, slot, start, clen) in enumerate(work):
            self.prefill_chunks += 1
            if self.watchdog and not bool(oks[i]):
                self.watchdog_trips += 1
                self._m_watchdog.inc()
                warnings.warn(
                    f"decode watchdog: non-finite prefill logits for "
                    f"request {req.rid} — quarantined")
                self._rt.event(req.rid, "watchdog_trip",
                               engine=self.instance,
                               why="nonfinite_prefill")
                self._fl.incident(
                    "watchdog", rid=req.rid,
                    extra={"engine": self.instance,
                           "why": "non-finite prefill logits"})
                self._finalize_active(req, "error", now)
                continue
            if start + clen < int(req.prompt.size):
                # mid-prompt: park until the next iteration's chunk —
                # decode interleaves in the meantime
                self._prefilling[slot]["start"] = start + clen
                self._rt.event(req.rid, "prefill_chunk",
                               engine=self.instance, slot=slot,
                               start=start, tokens=clen)
                continue
            self._prefilling.pop(slot, None)
            self._prefill_order.remove(slot)
            self.cache.positions[slot] = int(req.prompt.size)
            if self._draft is not None and (
                    self._spec_min_accept is None
                    or self._spec_accept_ewma >= self._spec_min_accept):
                # gate closed -> skip the draft-side prefill dispatch:
                # the lane stays at pos 0 and the catchup arithmetic in
                # _step_speculative feeds prompt + stream through the
                # draft's bulk-catchup program if a probe ever reopens
                # speculation, so a junk draft costs nothing per
                # admission while gated off
                self._draft.admit(slot, req.prompt)
            if self.prefix_cache is not None:
                self.prefix_cache.intern(req.prompt, slot)
            self.prefills += 1
            self._m_prefill_iters.inc()
            self._rt.event(req.rid, "prefill_end", engine=self.instance,
                           slot=slot, ok=True)
            tok = int(toks[i])
            forced = req.next_replay()
            if forced is not None:
                tok = forced
                self._last_tokens[slot] = tok
                self._absorb_replay(req, tok)
            else:
                self._last_tokens[slot] = tok
                self._emit(req, tok, now)
                produced += 1
            self._maybe_retire(req, tok, now)
        return produced

    def step(self):
        """One scheduler iteration: expire/admit/prefill, then one fused
        decode step for everything in flight.  Returns the number of
        tokens produced."""
        produced = 0
        self._expire(self._now())
        if self._paged:
            produced += self._prefill_paged()
            if self._spec_k and self._spec_gate():
                return produced + self._step_speculative()
            return produced + self._step_decode()
        # 1) admission: prefill up to the budget into free slots
        for req, slot in self.scheduler.admit():
            req.t_admit = self._now()
            self._rt.event(req.rid, "admitted", engine=self.instance,
                           slot=slot)
            padded, _ = pad_prompts([req.prompt],
                                    pad_to=self.max_prompt_len)
            self._rt.event(req.rid, "prefill_start",
                           engine=self.instance, slot=slot,
                           prompt_len=int(req.prompt.size))
            try:
                with self._tr.span("serve_prefill"):
                    k, v, tok, ok = self._prefill_fn(
                        self.params, self.cache.k, self.cache.v,
                        jnp.asarray(padded), req.prompt.size, slot,
                        self._next_key())
                    self.cache.update(k, v)
                    self.cache.positions[slot] = req.prompt.size
                    tok = int(np.asarray(tok))
                    ok = bool(np.asarray(ok))
            except Exception as e:
                if not self.watchdog:
                    raise
                self.watchdog_trips += 1
                self._m_watchdog.inc()
                why = (f"prefill of request {req.rid} raised "
                       f"{type(e).__name__}: {e}")
                warnings.warn(
                    f"decode watchdog: {why} — quarantined")
                self._rt.event(req.rid, "watchdog_trip",
                               engine=self.instance, why="prefill_raise")
                self._fl.incident("watchdog", rid=req.rid,
                                  extra={"engine": self.instance,
                                         "why": why})
                self._finalize_active(req, "error", self._now())
                continue
            self.prefills += 1
            self._m_prefill_iters.inc()
            now = self._now()
            self._rt.event(req.rid, "prefill_end", engine=self.instance,
                           slot=slot, ok=bool(ok))
            if self.watchdog and not ok:
                self.watchdog_trips += 1
                self._m_watchdog.inc()
                warnings.warn(
                    f"decode watchdog: non-finite prefill logits for "
                    f"request {req.rid} — quarantined")
                self._rt.event(req.rid, "watchdog_trip",
                               engine=self.instance,
                               why="nonfinite_prefill")
                self._fl.incident(
                    "watchdog", rid=req.rid,
                    extra={"engine": self.instance,
                           "why": "non-finite prefill logits"})
                self._finalize_active(req, "error", now)
                continue
            forced = req.next_replay()
            if forced is not None:
                # failover replay: the first generated token is already
                # known (and was already delivered) — force it instead
                # of emitting.  For a greedy request the computed ``tok``
                # equals ``forced`` (same executable, same prompt); for
                # sampled requests the sibling's key stream differs and
                # forcing is what keeps the stream consistent.
                tok = forced
                self._last_tokens[slot] = tok
                self._absorb_replay(req, tok)
            else:
                self._last_tokens[slot] = tok
                self._emit(req, tok, now)
                produced += 1
            self._maybe_retire(req, tok, now)
        return produced + self._step_decode()

    def _step_decode(self):
        """One fused decode iteration over every active slot (shared by
        the slot and paged paths; the paged call swaps the PRNG key for
        block-table + per-slot sampling operands and skips slots whose
        prompt is still mid-chunked-prefill)."""
        produced = 0
        live = len(self.scheduler.running)
        if live:
            self.peak_active = max(self.peak_active, live)
            self.peak_live_tokens = max(self.peak_live_tokens,
                                        int(self.cache.positions.sum()))
        slots = self.scheduler.active_slots()
        if self._paged:
            # mid-prefill slots hold pages but have no decodable token
            # yet — decode proceeds AROUND them (that's the chunked
            # interleaving), their lanes masked to the sentinel page
            slots = [s for s in slots if s not in self._prefilling]
        if slots:
            active = np.zeros(self.cache.n_slots, bool)
            active[slots] = True
            # the active mask only changes at request boundaries; reuse
            # the device copy across the (long) decode runs in between
            akey = active.tobytes()
            if self._dev_active[0] != akey:
                self._dev_active = (akey, self._dev_put(active))
            dev_active = self._dev_active[1]
            occ = len(slots) / self.cache.n_slots
            self.occupancy.append(occ)
            self._m_occ.set(occ)
            if self._paged and (self.cache.pages_shared
                                or self.cache.cow_guard):
                for s in slots:
                    pos = int(self.cache.positions[s])
                    if self.cache.pages_shared:
                        self.cache.ensure_writable(s, pos, 1)
                    if self.cache.cow_guard:
                        self.cache.assert_writable(s, pos, 1)
            try:
                with self._tr.span("serve_decode"):
                    # _last_tokens is mutated in place per emitted token,
                    # so upload a SNAPSHOT: on the CPU backend
                    # jnp.asarray may alias the host buffer / defer the
                    # copy, and the post-dispatch mutation raced the
                    # pending read (nondeterministic streams — the
                    # tier-1 serving flake)
                    if self._paged:
                        if self._dev_sampling is None:
                            self._dev_sampling = (
                                self._dev_put(self._temps.copy()),
                                self._dev_put(self._topks.copy()),
                                self._dev_put(self._seeds.copy()))
                        temps, topks, seeds = self._dev_sampling
                        k, v, nxt, slot_ok = self._step_fn(
                            self.params, self.cache.k, self.cache.v,
                            self._dev_put(self._last_tokens.copy()),
                            self.cache.device_positions(),
                            self.cache.device_block_tables(),
                            dev_active, temps, topks, seeds)
                    else:
                        k, v, nxt, slot_ok = self._step_fn(
                            self.params, self.cache.k, self.cache.v,
                            jnp.asarray(self._last_tokens.copy()),
                            self.cache.device_positions(),
                            dev_active, self._next_key())
                    self.cache.update(k, v)
                    self.cache.advance(slots)
                    # materialize INSIDE the span: this is where the
                    # host actually waits for the decode iteration
                    nxt = np.asarray(nxt)
                    slot_ok = np.asarray(slot_ok)
            except Exception as e:
                if not self.watchdog:
                    raise
                self._quarantine_all(
                    f"decode step raised {type(e).__name__}: {e}",
                    self._now())
                return produced
            self.decode_steps += 1
            self._m_decode_iters.inc()
            now = self._now()
            for slot in slots:
                req = self.scheduler.running[slot]
                if self.watchdog and not slot_ok[slot]:
                    # quarantine: only THIS slot is poisoned (slots
                    # attend their own cache rows only); the bad token
                    # is never emitted, the slot is reclaimed, and the
                    # other streams stay bitwise identical
                    self.watchdog_trips += 1
                    self._m_watchdog.inc()
                    warnings.warn(
                        f"decode watchdog: non-finite logits in slot "
                        f"{slot} (request {req.rid}) — quarantined")
                    self._rt.event(req.rid, "watchdog_trip",
                                   engine=self.instance, slot=slot,
                                   why="nonfinite_decode")
                    self._fl.incident(
                        "watchdog", rid=req.rid,
                        extra={"engine": self.instance, "slot": slot,
                               "why": "non-finite decode logits"})
                    self._finalize_active(req, "error", now)
                    continue
                forced = req.next_replay()
                if forced is not None:
                    # teacher-forced replay step: the cache row written
                    # by this iteration is a function of the FED token,
                    # so forcing the known token rebuilds the exact KV
                    # state of the original run
                    tok = forced
                    self._last_tokens[slot] = tok
                    self._absorb_replay(req, tok)
                    # ONE timeline event per iteration per request —
                    # slot + running token count, never per-token spam
                    self._rt.event(req.rid, "decode_iter",
                                   engine=self.instance, slot=slot,
                                   tokens=len(req.tokens), replayed=True)
                    self._maybe_retire(req, tok, now)
                    continue
                tok = int(nxt[slot])
                self._last_tokens[slot] = tok
                self._emit(req, tok, now)
                produced += 1
                self._rt.event(req.rid, "decode_iter",
                               engine=self.instance, slot=slot,
                               tokens=len(req.tokens))
                self._maybe_retire(req, tok, now)
        return self._leak_sweep(produced)

    def _leak_sweep(self, produced):
        """Leak sweep (end of every decode iteration): a slot owned by
        nobody can never be retired through the request path — reclaim
        it so the pool cannot starve (cheap: one int comparison in the
        healthy case)."""
        if (self.watchdog
                and self.cache.n_active != len(self.scheduler.running)):
            reclaimed = self.scheduler.reconcile()
            if reclaimed:
                self.slot_leaks_reclaimed += reclaimed
                self._m_leaks.inc(reclaimed)
                warnings.warn(
                    f"slot reconcile: reclaimed {reclaimed} leaked KV "
                    "slot(s)")
        return produced

    def _spec_gate(self):
        """Adaptive speculation gate: True -> run the verify window
        this iteration.  With no threshold configured speculation is
        unconditional; otherwise fall back to plain decode while the
        accepted-tokens-per-iteration EWMA sags below it, re-probing
        every ``spec_probe_every`` iterations so recovered acceptance
        re-enables speculation.  The fallback runs the SAME shared
        step executable as the non-speculative twin, so the floor is
        plain-decode throughput minus probe overhead — a slope, never
        a cliff."""
        if self._spec_min_accept is None:
            return True
        if self._spec_accept_ewma >= self._spec_min_accept:
            self._spec_since_probe = 0
            return True
        self._spec_since_probe += 1
        if self._spec_since_probe >= self._spec_probe_every:
            self._spec_since_probe = 0
            return True
        return False

    def _step_speculative(self):
        """One speculative iteration: the draft proposes ``spec_k``
        candidates per slot, ONE fused verify step teacher-forces the
        whole ``[S, W]`` window (W = spec_k + 1, the PR 6 replay path
        widened), and the host commits the accepted prefix — bitwise
        the tokens the plain decode loop would have emitted, in fewer
        dispatches.  Rejected rows need no device rollback: they sit
        beyond the committed position, exactly the stale rows the
        ``col <= position`` mask never attends, and the next write at
        those positions overwrites them (``kv_cache.advance_by``).
        Failover replay slots spend their known continuation as window
        candidates first, so replay accepts at full width and stays
        bit-exact mid-speculation."""
        produced = 0
        live = len(self.scheduler.running)
        if live:
            self.peak_active = max(self.peak_active, live)
            self.peak_live_tokens = max(self.peak_live_tokens,
                                        int(self.cache.positions.sum()))
        slots = [s for s in self.scheduler.active_slots()
                 if s not in self._prefilling]
        if not slots:
            return self._leak_sweep(produced)
        kk = self._spec_k
        window = kk + 1
        n = self.cache.n_slots
        active = np.zeros(n, bool)
        active[slots] = True
        akey = active.tobytes()
        if self._dev_active[0] != akey:
            self._dev_active = (akey, self._dev_put(active))
        dev_active = self._dev_active[1]
        occ = len(slots) / n
        self.occupancy.append(occ)
        self._m_occ.set(occ)
        if self._dev_sampling is None:
            self._dev_sampling = (self._dev_put(self._temps.copy()),
                                  self._dev_put(self._topks.copy()),
                                  self._dev_put(self._seeds.copy()))
        temps, topks, seeds = self._dev_sampling
        # window candidates: replay remainder first (failover — the
        # stream continuation is KNOWN and accepts by construction),
        # then draft proposals
        rems = {}
        need_draft = False
        for s in slots:
            req = self.scheduler.running[s]
            rem = ([] if req.replay is None else
                   [int(t) for t in req.replay[
                       req._replay_pos:req._replay_pos + kk]])
            rems[s] = rem
            if len(rem) < kk:
                need_draft = True
        props = None
        try:
            if self._draft is not None:
                work = []
                for s in slots:
                    req = self.scheduler.running[s]
                    dp = int(self._draft.pos[s])
                    p = int(req.prompt.size)
                    if dp < p:
                        cat = ([int(t) for t in req.prompt[dp:]]
                               + list(req.tokens))
                    else:
                        cat = list(req.tokens[dp - p:])
                    work.append((s, cat))
                props = self._draft.propose(work, temps, topks, seeds)
            elif need_draft:
                props = np.asarray(self._draft_fn(
                    self.params, self.cache.k, self.cache.v,
                    self._dev_put(self._last_tokens.copy()),
                    self.cache.device_positions(),
                    self.cache.device_block_tables(),
                    temps, topks, seeds))
        except Exception as e:
            if not self.watchdog:
                raise
            self._quarantine_all(
                f"speculative draft raised {type(e).__name__}: {e}",
                self._now())
            return produced
        toks = np.zeros((n, window), np.int32)
        toks[:, 0] = self._last_tokens
        for s in slots:
            cand = list(rems[s])
            if props is not None:
                cand += [int(props[s, i]) for i in range(len(cand), kk)]
                d = kk - len(rems[s])
                if d > 0:
                    self.spec_proposed += d
                    self._m_spec_proposed.inc(d)
            else:
                cand += [0] * (kk - len(cand))
            toks[s, 1:] = cand
            pos = int(self.cache.positions[s])
            if self.cache.pages_shared:
                self.cache.ensure_writable(s, pos, window)
            if self.cache.cow_guard:
                self.cache.assert_writable(s, pos, window)
        try:
            with self._tr.span("serve_decode"):
                k, v, picks, oks = self._verify_fn(
                    self.params, self.cache.k, self.cache.v,
                    self._dev_put(toks), self.cache.device_positions(),
                    self.cache.device_block_tables(), dev_active,
                    temps, topks, seeds)
                self.cache.update(k, v)
                picks = np.asarray(picks)
                oks = np.asarray(oks)
        except Exception as e:
            if not self.watchdog:
                raise
            self._quarantine_all(
                f"speculative verify raised {type(e).__name__}: {e}",
                self._now())
            return produced
        self.decode_steps += 1
        self.spec_steps += 1
        self._m_decode_iters.inc()
        now = self._now()
        total_m = 0
        for s in slots:
            req = self.scheduler.running[s]
            r = len(rems[s])
            m = 0
            finished = False
            for j in range(window):
                if self.watchdog and not oks[s, j]:
                    self.watchdog_trips += 1
                    self._m_watchdog.inc()
                    warnings.warn(
                        f"decode watchdog: non-finite logits in slot "
                        f"{s} (request {req.rid}) — quarantined")
                    self._rt.event(req.rid, "watchdog_trip",
                                   engine=self.instance, slot=s,
                                   why="nonfinite_decode")
                    self._fl.incident(
                        "watchdog", rid=req.rid,
                        extra={"engine": self.instance, "slot": s,
                               "why": "non-finite decode logits"})
                    self._finalize_active(req, "error", now)
                    finished = True
                    break
                forced = req.next_replay()
                if forced is not None:
                    tok = int(forced)
                    self._last_tokens[s] = tok
                    self._absorb_replay(req, tok)
                else:
                    tok = int(picks[s, j])
                    self._last_tokens[s] = tok
                    self._emit(req, tok, now)
                    produced += 1
                m += 1
                done_eos = (req.eos_id is not None
                            and tok == req.eos_id)
                if done_eos or len(req.tokens) >= req.max_new:
                    self._finalize_active(
                        req, "eos" if done_eos else "max_new", now)
                    finished = True
                    break
                # the chain rule: window step j+1 fed candidate
                # toks[s, j+1]; its pick is the stream continuation iff
                # that candidate IS the token just committed
                if j + 1 < window and int(toks[s, j + 1]) == tok:
                    if j >= r:      # a draft-origin candidate survived
                        self.spec_accepted += 1
                        self._m_spec_accepted.inc()
                    continue
                break
            total_m += m
            if not finished:
                self.cache.advance_by(s, m)
                self._rt.event(req.rid, "decode_iter",
                               engine=self.instance, slot=s,
                               tokens=len(req.tokens), spec=m)
        mean_m = total_m / len(slots)
        self._spec_accept_ewma += 0.25 * (mean_m
                                          - self._spec_accept_ewma)
        return self._leak_sweep(produced)

    def run(self, max_iterations=None):
        """Step until queue and slots drain; returns iterations used."""
        it = 0
        while not self.scheduler.idle:
            if max_iterations is not None and it >= max_iterations:
                raise RuntimeError(
                    f"engine did not drain in {max_iterations} iterations")
            self.step()
            it += 1
        return it

    def generate_many(self, prompts, max_new, eos_id=None):
        """Synchronous batch API: submit all, drain, return each
        request's generated ids (prompt excluded)."""
        reqs = [self.submit(p, max_new, eos_id=eos_id) for p in prompts]
        # worst case every request runs alone to max_len
        self.run(max_iterations=(len(reqs) + 1) * (self.max_len + 2))
        return [r.result() for r in reqs]

    def stream(self, prompt, max_new, eos_id=None, ttl=None):
        """Generator API: yields tokens as the engine produces them
        (pumping the engine between yields; other in-flight requests
        advance too)."""
        req = self.submit(prompt, max_new, eos_id=eos_id, ttl=ttl)
        emitted = 0
        guard = (self.max_len + 2) * (len(self.scheduler.queue)
                                      + self.cache.n_slots + 1)
        it = 0
        while emitted < len(req.tokens) or not req.finished:
            if emitted < len(req.tokens):
                emitted += 1
                yield req.tokens[emitted - 1]
                continue
            if it >= guard:
                raise RuntimeError("stream did not make progress")
            self.step()
            it += 1

    def reset_stats(self):
        """Clear per-request records and step counters (NOT the trace
        counters — retraces after a warmup are exactly what the
        compile-once guard must still see)."""
        self.records = []
        self.occupancy = []
        self.decode_steps = 0
        self.prefills = 0
        self.prefill_chunks = 0
        self.peak_active = 0
        self.peak_live_tokens = 0
        self.cancellations = 0
        self.expirations = 0
        self.watchdog_trips = 0
        self.slot_leaks_reclaimed = 0
        self.streams_detached = 0
        self.replayed_tokens = 0
        self.spec_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0

    # -- reporting ---------------------------------------------------------
    def stats(self):
        occ = float(np.mean(self.occupancy)) if self.occupancy else 0.0
        out = {"n_slots": self.cache.n_slots,
                "mean_occupancy": round(occ, 4),
                "decode_steps": self.decode_steps,
                "prefills": self.prefills,
                "prefill_chunks": self.prefill_chunks,
                "peak_active": self.peak_active,
                "peak_live_tokens": self.peak_live_tokens,
                "requests_finished": len(self.records),
                "slot_allocs": self.cache.alloc_count,
                "slot_frees": self.cache.free_count,
                "rejections": self.scheduler.rejected,
                "queue_depth_peak": self.scheduler.queue_depth_peak,
                "cancellations": self.cancellations,
                "expirations": self.expirations,
                "watchdog_trips": self.watchdog_trips,
                "slot_leaks_reclaimed": self.slot_leaks_reclaimed,
                "streams_detached": self.streams_detached,
                "replayed_tokens": self.replayed_tokens,
                "trace_counts": self.trace_counts}
        if self._paged:
            out["pages"] = self.cache.occupancy()
        if self._spec_k:
            prop = self.spec_proposed
            out["spec"] = {
                "k": self._spec_k,
                "draft": ("model" if self._draft is not None
                          else f"self[{self._draft_layers}]"),
                "steps": self.spec_steps,
                "proposed": prop,
                "accepted": self.spec_accepted,
                "acceptance_rate": (round(self.spec_accepted / prop, 4)
                                    if prop else 0.0),
                "accepted_per_step_ewma": round(
                    self._spec_accept_ewma, 4)}
        if self.prefix_cache is not None:
            out["prefix"] = self.prefix_cache.stats()
        if self.mesh is not None:
            out["mesh"] = {
                "tp": self._tp,
                "devices": list(_shd.device_ids(self.mesh)),
                "kv_per_chip_bytes": _shd.per_chip_bytes(
                    (self.cache.k, self.cache.v)),
                "param_per_chip_bytes": _shd.per_chip_bytes(self.params)}
        return out
