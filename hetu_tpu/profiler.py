"""Profiling + cost simulation (reference: python/hetu/profiler.py —
`HetuProfiler` :55 per-op replay timing with synthetic inputs and zipf key
sampling for embedding ops; `NCCLProfiler` :390 collective micro-benchmarks;
`HetuSimulator` :609 cached per-op times feeding the auto-parallel
searchers).

TPU redesign: per-op replay compiles each node's compute as its own jitted
function on synthetic inputs (XLA owns streams, so CUDA-event timing becomes
wall-clock around block_until_ready); whole-step timing wraps the compiled
step.  The simulator combines measured per-op times (cached on disk keyed by
op type + shapes, like /tmp/hetu_cached_exetime.bin) with an analytic
roofline + collective model so searchers can score sharding choices without
running them.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from .graph.node import Op, PlaceholderOp, VariableOp, find_topo_sort
from .graph.trace import TraceContext


def _sync(out):
    """Materialize a result to end a timing window: through the dev
    tunnel, jax.block_until_ready has been observed returning before the
    work actually finishes (BASELINE.md methodology note)."""
    np.asarray(jax.tree_util.tree_leaves(out)[0])


# ---------------------------------------------------------------------------
# shape inference over the graph


def shape_map(eval_nodes, feed_shapes=None):
    """{node: ShapeDtypeStruct} for every node, via per-op jax.eval_shape.

    ``feed_shapes``: optional {placeholder_name: shape} overriding declared
    shapes (the reference re-infers on feed-shape change, executor.py:938).
    """
    feed_shapes = feed_shapes or {}
    ctx = TraceContext(key=jax.random.key(0), training=False)
    shapes = {}
    for node in find_topo_sort(eval_nodes):
        if isinstance(node, PlaceholderOp):
            shape = feed_shapes.get(node.name, node.shape)
            assert shape is not None, f"{node.name} has no shape"
            shapes[node] = jax.ShapeDtypeStruct(tuple(shape), node.dtype)
        elif isinstance(node, VariableOp):
            shapes[node] = jax.ShapeDtypeStruct(tuple(node.shape),
                                                node.dtype)
        elif hasattr(node, "_compute_with_env"):
            shapes[node] = None  # stateful/bundle nodes: skip
        else:
            ins = [shapes[i] for i in node.inputs]
            if any(s is None for s in ins):
                shapes[node] = None
                continue
            try:
                shapes[node] = jax.eval_shape(
                    lambda *xs: node._compute(list(xs), ctx), *ins)
            except Exception:
                shapes[node] = None
    return shapes


# ---------------------------------------------------------------------------
# FLOP / byte estimation (drives the analytic cost model)


def op_kind(node):
    """Semantic op name: SimpleOps carry op_kind; class name otherwise."""
    return getattr(node, "op_kind", type(node).__name__).lower()


def estimate_flops(node, shapes):
    """Rough FLOPs of one op given the shape map (0 for unknown/cheap)."""
    out = shapes.get(node)
    tname = op_kind(node)
    ins = [shapes.get(i) for i in node.inputs]
    if out is None:
        return 0.0
    n_out = float(np.prod(out.shape)) if out.shape else 1.0
    if "matmul" in tname or "linear" in tname:
        if ins and ins[0] is not None:
            k = float(ins[0].shape[-1])
            return 2.0 * n_out * k
        return 2.0 * n_out
    if "conv" in tname and ins and len(ins) > 1 and ins[1] is not None:
        w = ins[1].shape
        if "hwio" in tname:          # (Kh, Kw, I, O): per-output-element
            k = float(np.prod(w[:2])) * w[2]   # Kh*Kw*I MACs
        else:                        # OIHW: drop the O dim
            k = float(np.prod(w[1:]))
        return 2.0 * n_out * k
    if "attention" in tname and ins and ins[0] is not None:
        b, h, s, d = ins[0].shape
        return 4.0 * b * h * s * s * d
    return n_out  # elementwise-ish


def tensor_bytes(struct):
    if struct is None:
        return 0
    return int(np.prod(struct.shape)) * struct.dtype.itemsize


# ---------------------------------------------------------------------------
# per-op replay profiler


def _synth_input(struct, rng, zipf_vocab=None):
    if np.issubdtype(struct.dtype, np.integer):
        hi = zipf_vocab or 1000
        # zipf-distributed keys for embedding realism (reference
        # profiler.py:143-165 uses zipf samplers for sparse ops)
        vals = np.minimum(rng.zipf(1.5, size=struct.shape), hi) - 1
        return jnp.asarray(vals, struct.dtype)
    return jnp.asarray(rng.standard_normal(struct.shape), struct.dtype)


class HetuProfiler:
    """Per-op replay timing (reference HetuProfiler.profile_all)."""

    def __init__(self, eval_nodes, feed_shapes=None, seed=0):
        self.eval_nodes = list(eval_nodes)
        self.shapes = shape_map(self.eval_nodes, feed_shapes)
        self.rng = np.random.default_rng(seed)

    def profile_op(self, node, repeats=5, warmup=1):
        """Compile node._compute alone and wall-clock it."""
        if (isinstance(node, (PlaceholderOp, VariableOp))
                or hasattr(node, "_compute_with_env")):
            return 0.0
        ins = [self.shapes.get(i) for i in node.inputs]
        if any(s is None for s in ins) or self.shapes.get(node) is None:
            return 0.0
        ctx = TraceContext(key=jax.random.key(0), training=False)
        fn = jax.jit(lambda *xs: node._compute(list(xs), ctx))
        args = [_synth_input(s, self.rng) for s in ins]
        try:
            for _ in range(warmup):
                out = fn(*args)
            _sync(out)
            t0 = time.perf_counter()
            for _ in range(repeats):
                out = fn(*args)
            _sync(out)
            return (time.perf_counter() - t0) / repeats
        except Exception:
            return 0.0

    def profile_all(self, repeats=5):
        """{node_name: seconds} over all computable nodes."""
        out = {}
        for node in find_topo_sort(self.eval_nodes):
            dt = self.profile_op(node, repeats=repeats)
            if dt > 0:
                out[node.name] = dt
        return out


class CommProfiler:
    """Collective micro-benchmarks over the current devices (reference
    NCCLProfiler :390 — allreduce/sendrecv sweeps feeding cost models)."""

    def __init__(self, mesh=None):
        self.mesh = mesh

    def bench_collective(self, kind="psum", nbytes=1 << 20, axis=None,
                         repeats=5):
        from jax.sharding import PartitionSpec as P
        from .platform import shard_map
        import jax.numpy as jnp
        mesh = self.mesh
        if mesh is None:
            return 0.0
        axis = axis or mesh.axis_names[0]
        n = mesh.shape[axis]
        elems = max(nbytes // 4, n)
        elems -= elems % n
        x = jnp.ones((elems,), jnp.float32)

        def body(v):
            if kind == "psum":
                return jax.lax.psum(v, axis)
            if kind == "all_gather":
                return jax.lax.all_gather(v, axis, tiled=True)
            if kind == "ppermute":
                return jax.lax.ppermute(
                    v, axis, [(i, (i + 1) % n) for i in range(n)])
            raise ValueError(kind)

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P(axis),
                               out_specs=P(axis) if kind == "ppermute"
                               else (P() if kind == "psum" else P())))
        out = fn(x)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = fn(x)
        _sync(out)
        return (time.perf_counter() - t0) / repeats


# ---------------------------------------------------------------------------
# simulator / cost model


class HetuSimulator:
    """Cost model for auto-parallel search (reference HetuSimulator :609).

    Combines: (a) measured per-op times cached on disk; (b) an analytic
    roofline (flops/peak, bytes/bandwidth) fallback; (c) a linear collective
    model time = latency + bytes/bandwidth scaled by the standard ring
    factor (k-1)/k over the participating axis size.
    """

    # conservative single-chip defaults; calibrate() overwrites from
    # measurement. Units: flops/s, bytes/s, seconds.
    peak_flops = 2e14          # bf16 MXU order of magnitude
    hbm_bw = 8e11
    ici_bw = 4.5e10            # per-link ICI, one direction
    ici_latency = 1e-6
    dcn_bw = 2.5e9
    dcn_latency = 2.5e-5

    def __init__(self, cache_path=None):
        self.cache_path = cache_path or os.path.join(
            os.path.expanduser("~"), ".hetu_tpu_exetime.json")
        self._cache = {}
        if os.path.exists(self.cache_path):
            try:
                with open(self.cache_path) as f:
                    self._cache = json.load(f)
            except Exception:
                self._cache = {}

    # -- measured-time cache ----------------------------------------------
    @staticmethod
    def _op_key(node, shapes):
        ins = [tuple(shapes[i].shape) if shapes.get(i) is not None else None
               for i in node.inputs]
        return f"{op_kind(node)}:{ins}"

    def record(self, eval_nodes, feed_shapes=None, repeats=5):
        prof = HetuProfiler(eval_nodes, feed_shapes)
        for node in find_topo_sort(eval_nodes):
            key = self._op_key(node, prof.shapes)
            if key not in self._cache:
                dt = prof.profile_op(node, repeats=repeats)
                if dt > 0:
                    self._cache[key] = dt
        self.save()
        return self._cache

    def save(self):
        try:
            with open(self.cache_path, "w") as f:
                json.dump(self._cache, f)
        except Exception:
            pass

    # -- analytic pieces ----------------------------------------------------
    def op_time(self, node, shapes, shard_factor=1.0):
        """Estimated seconds for one op with its work divided shard_factor
        ways (measured if cached, else roofline)."""
        key = self._op_key(node, shapes)
        if key in self._cache:
            return self._cache[key] / shard_factor
        flops = estimate_flops(node, shapes) / shard_factor
        bytes_moved = (sum(tensor_bytes(shapes.get(i))
                           for i in node.inputs)
                       + tensor_bytes(shapes.get(node))) / shard_factor
        return max(flops / self.peak_flops, bytes_moved / self.hbm_bw)

    def collective_time(self, nbytes, axis_size, kind="all_reduce",
                        over="ici"):
        if axis_size <= 1:
            return 0.0
        bw = self.ici_bw if over == "ici" else self.dcn_bw
        lat = self.ici_latency if over == "ici" else self.dcn_latency
        k = axis_size
        factor = {"all_reduce": 2.0 * (k - 1) / k,
                  "all_gather": (k - 1) / k,
                  "reduce_scatter": (k - 1) / k,
                  "all_to_all": (k - 1) / k,
                  "p2p": 1.0}[kind]
        return lat * (k - 1) + factor * nbytes / bw

    def graph_time(self, eval_nodes, shapes=None, shard_factors=None):
        """Sum of per-op estimates (the searchers add comm terms)."""
        shapes = shapes or shape_map(eval_nodes)
        shard_factors = shard_factors or {}
        total = 0.0
        for node in find_topo_sort(eval_nodes):
            if isinstance(node, (PlaceholderOp, VariableOp)):
                continue
            total += self.op_time(node, shapes,
                                  shard_factors.get(node, 1.0))
        return total

    def calibrate(self, size=2048, repeats=3):
        """Measure actual matmul throughput to scale the roofline."""
        x = jnp.ones((size, size), jnp.bfloat16)
        fn = jax.jit(lambda a: a @ a)
        _sync(fn(x))
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = fn(x)
        _sync(out)
        dt = (time.perf_counter() - t0) / repeats
        self.peak_flops = 2.0 * size ** 3 / dt
        return self.peak_flops
