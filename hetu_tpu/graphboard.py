"""Graph visualizer (reference: python/graphboard/graph2fig.py + index.html
— dumps the op DAG to a figure served by a small page).

Here the DAG renders to (a) Graphviz DOT text and (b) a dependency-free
standalone HTML file with an inline SVG (nodes positioned by topo depth), so
`dump_html` works with zero extra packages on a TPU VM.
"""

from __future__ import annotations

import html

from .graph.node import Op, PlaceholderOp, VariableOp, find_topo_sort


def _dot_escape(s):
    return s.replace("\\", "\\\\").replace('"', '\\"')


def _node_label(n):
    kind = getattr(n, "op_kind", type(n).__name__)
    return _dot_escape(f"{n.name}") + "\\n" + _dot_escape(f"[{kind}]")


def _node_color(n):
    if isinstance(n, PlaceholderOp):
        return "#8ecae6"          # inputs: blue
    if isinstance(n, VariableOp):
        return "#ffb703" if n.trainable else "#e9c46a"   # params: orange
    if getattr(n, "is_stateful", False):
        return "#e76f51"          # stateful: red
    return "#d8e2dc"


def graph_to_dot(eval_nodes, name="hetu_graph"):
    """DAG -> Graphviz DOT text."""
    topo = find_topo_sort(list(eval_nodes))
    lines = [f"digraph {name} {{", "  rankdir=TB;",
             "  node [shape=box, style=filled, fontsize=10];"]
    for n in topo:
        lines.append(
            f'  n{n.id} [label="{_node_label(n)}", '
            f'fillcolor="{_node_color(n)}"];')
    for n in topo:
        for i in n.inputs:
            lines.append(f"  n{i.id} -> n{n.id};")
    lines.append("}")
    return "\n".join(lines)


def _layout(topo):
    """Topo-depth layered layout: (x, y) per node id."""
    depth = {}
    for n in topo:
        depth[n.id] = (max((depth[i.id] for i in n.inputs), default=-1) + 1)
    buckets = {}
    for n in topo:
        buckets.setdefault(depth[n.id], []).append(n)
    pos = {}
    for d, nodes in buckets.items():
        for i, n in enumerate(nodes):
            pos[n.id] = (60 + i * 170, 50 + d * 90)
    return pos


def graph_to_svg(eval_nodes):
    topo = find_topo_sort(list(eval_nodes))
    if not topo:
        return ('<svg xmlns="http://www.w3.org/2000/svg" width="200" '
                'height="40"><text x="10" y="25">(empty graph)</text></svg>')
    pos = _layout(topo)
    w = max(x for x, _ in pos.values()) + 180
    h = max(y for _, y in pos.values()) + 90
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
             f'height="{h}" font-family="monospace" font-size="10">']
    for n in topo:
        x1, y1 = pos[n.id]
        for i in n.inputs:
            x0, y0 = pos[i.id]
            parts.append(
                f'<line x1="{x0 + 75}" y1="{y0 + 36}" x2="{x1 + 75}" '
                f'y2="{y1}" stroke="#888" stroke-width="1"/>')
    for n in topo:
        x, y = pos[n.id]
        kind = getattr(n, "op_kind", type(n).__name__)
        parts.append(
            f'<rect x="{x}" y="{y}" width="150" height="36" rx="5" '
            f'fill="{_node_color(n)}" stroke="#333"/>'
            f'<text x="{x + 75}" y="{y + 15}" text-anchor="middle">'
            f'{html.escape(n.name[:22])}</text>'
            f'<text x="{x + 75}" y="{y + 29}" text-anchor="middle" '
            f'fill="#555">{html.escape(kind[:22])}</text>')
    parts.append("</svg>")
    return "".join(parts)


def dump_html(eval_nodes, path, title="hetu_tpu graph"):
    """Write a self-contained DAG page (reference graphboard/index.html)."""
    svg = graph_to_svg(eval_nodes)
    dot = graph_to_dot(eval_nodes)
    doc = (f"<!doctype html><html><head><meta charset='utf-8'>"
           f"<title>{html.escape(title)}</title></head><body>"
           f"<h2>{html.escape(title)}</h2>{svg}"
           f"<h3>DOT source</h3><pre>{html.escape(dot)}</pre>"
           f"</body></html>")
    with open(path, "w") as f:
        f.write(doc)
    return path
