"""Distributed GNN tooling: graph partitioning + neighbor-sampling
dataloaders (reference: examples/gnn/gnn_tools/part_graph.py via the
GraphMix submodule, python/hetu/dataloader.py:253 GNNDataLoaderOp).

The compute side lives in models/gnn.py (gcn_conv, DistGCN15D); this
package owns the data side: cutting a graph into device-sized parts and
streaming sampled subgraph batches.
"""

from .partition import GraphPartition, partition_graph, save_partition, \
    load_partition
from .sampling import NeighborSampler, GNNDataLoader
from .datasets import (GraphDataset, read_edge_list, load_cora,
                       load_graph_npz, save_graph_npz, make_split,
                       make_cora_sample)

__all__ = ["GraphPartition", "partition_graph", "save_partition",
           "load_partition", "NeighborSampler", "GNNDataLoader",
           "GraphDataset", "read_edge_list", "load_cora",
           "load_graph_npz", "save_graph_npz", "make_split",
           "make_cora_sample"]
