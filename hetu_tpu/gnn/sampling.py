"""Neighbor sampling + the GNN dataloader (reference GNNDataLoaderOp,
python/hetu/dataloader.py:253 — a double-buffered graph feed where
``step(next_graph)`` publishes the next sampled subgraph while the
current one trains).

TPU shape discipline: every sampled batch is RECTANGULAR — per-parent
fanout sampling (GraphSAGE-style, duplicates allowed) gives exactly
``B*f1 + B*f1*f2 + ...`` edges, and the deduplicated node array is
padded to the fixed worst-case ``B*(1 + f1 + f1*f2 + ...)`` — so ONE
compiled program serves every batch (variable-degree CSR batches would
retrace XLA every step)."""

from __future__ import annotations

import threading

import numpy as np

from .partition import _build_csr


class NeighborSampler:
    """k-hop per-parent neighbor sampling with fixed fanouts.
    Deterministic for a given seed.

    Returns per batch (all shapes fixed for a fixed batch size):
      nodes     [M]  original ids (seeds first; positions >= num_nodes
                     are padding — no edge touches them).  M =
                     B*(1 + f1 + f1*f2 + ...)
      src, dst  [E]  edges in LOCAL subgraph indices, dst-owned form;
                     E = B*(f1 + f1*f2 + ...).  Isolated parents get
                     self-loop edges.
      num_seeds      B (predictions read nodes[:B])
      num_nodes      count of REAL (non-padding) entries in ``nodes``
    """

    def __init__(self, src, dst, num_nodes, fanouts=(10, 10), seed=0):
        self.adj_start, self.adj = _build_csr(src, dst, num_nodes)
        self.graph_nodes = num_nodes
        self.fanouts = tuple(fanouts)
        self.rng = np.random.default_rng(seed)

    def node_budget(self, batch_size):
        m, layer = batch_size, batch_size
        for f in self.fanouts:
            layer *= f
            m += layer
        return m

    def sample(self, seeds):
        seeds = np.asarray(seeds, np.int64)
        nodes = list(seeds)
        local = {int(s): i for i, s in enumerate(seeds)}
        src_l, dst_l = [], []
        # frontier keeps DUPLICATES: per-parent fanout => fixed edge count
        frontier = [int(s) for s in seeds]
        for fanout in self.fanouts:
            nxt = []
            for u in frontier:
                neigh = self.adj[self.adj_start[u]:self.adj_start[u + 1]]
                if len(neigh) == 0:
                    picked = np.full(fanout, u, np.int64)   # self-loops
                else:
                    picked = self.rng.choice(neigh, size=fanout,
                                             replace=True)
                for v in picked:
                    v = int(v)
                    if v not in local:
                        local[v] = len(nodes)
                        nodes.append(v)
                    src_l.append(local[v])
                    dst_l.append(local[u])
                    nxt.append(v)
            frontier = nxt
        num_real = len(nodes)
        budget = self.node_budget(len(seeds))
        # pad with a dummy original id (0) at positions no edge touches:
        # feature gathers stay rectangular, results for pads are ignored
        nodes = np.asarray(nodes + [0] * (budget - num_real), np.int64)
        return {"nodes": nodes,
                "src": np.asarray(src_l, np.int64),
                "dst": np.asarray(dst_l, np.int64),
                "num_seeds": len(seeds),
                "num_nodes": num_real}


class GNNDataLoader:
    """Double-buffered sampled-subgraph stream (GNNDataLoaderOp role).

    A background thread samples batch t+1 while batch t trains —
    ``__next__`` swaps the buffers, exactly the reference's
    graph/nxt_graph classmethod pair, minus the globals.  Worker
    exceptions re-raise in the consumer thread."""

    _END = object()

    def __init__(self, sampler, train_nodes, batch_size, *, seed=0,
                 drop_remainder=True):
        self.sampler = sampler
        self.train_nodes = np.asarray(train_nodes, np.int64)
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.rng = np.random.default_rng(seed)
        self._order = None
        self._cursor = 0
        self._next = self._END
        self._error = None
        self._thread = None

    def __iter__(self):
        if self._thread is not None:
            self._thread.join()   # a prior partial epoch's in-flight
            self._error = None    # worker must not race the reset below
        self._order = self.rng.permutation(self.train_nodes)
        self._cursor = 0
        self._prefetch()
        return self

    def _sample_next(self):
        if self._cursor >= len(self._order):
            return self._END
        end = self._cursor + self.batch_size
        if end > len(self._order) and self.drop_remainder:
            return self._END
        seeds = self._order[self._cursor:end]
        self._cursor = end
        return self.sampler.sample(seeds)

    def _prefetch(self):
        def work():
            try:
                self._next = self._sample_next()
            except BaseException as e:   # surfaced in __next__
                self._error = e
                self._next = self._END
        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def __next__(self):
        self._thread.join()
        if self._error is not None:
            err, self._error = self._error, None
            raise err
        batch = self._next
        if batch is self._END:
            raise StopIteration
        self._prefetch()           # overlap next sample with training
        return batch
