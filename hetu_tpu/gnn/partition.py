"""Graph partitioning for distributed GNN training.

Reference: examples/gnn/gnn_tools/part_graph.py:1 calls GraphMix's
``part_graph`` (a METIS wrapper) to cut the node set into ``nparts``
balanced parts with small edge cut, writing per-part directories + a
meta file.  The GraphMix submodule is empty in the snapshot, so this is
a fresh implementation of the same role:

  * ``partition_graph`` — balanced low-edge-cut partitioning via a
    BFS-ordered linear-deterministic-greedy (LDG) stream pass with a
    refinement sweep (the classic streaming alternative to multilevel
    METIS; deterministic for a fixed seed).
  * ``GraphPartition`` — the result: part assignment, a node
    permutation making parts CONTIGUOUS (what the TPU path wants: a
    block-sharded adjacency is exactly "each device owns one contiguous
    part"), per-part local edge lists, and halo (remote-neighbor) ids.
  * ``save_partition`` / ``load_partition`` — one ``.npz`` per part +
    ``meta.json`` (the part_graph output-directory role).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np


@dataclass
class GraphPartition:
    nparts: int
    num_nodes: int
    part: np.ndarray          # [N] part id per ORIGINAL node id
    perm: np.ndarray          # [N] original id -> permuted position
    inv_perm: np.ndarray      # [N] permuted position -> original id
    offsets: np.ndarray       # [nparts+1] part boundaries in permuted order
    # per part, ORIGINAL ids of remote neighbors this part reads (halo)
    halos: list = field(default_factory=list)
    # per part, local edges (src, dst) in ORIGINAL ids, dst owned by part
    local_edges: list = field(default_factory=list)

    def part_nodes(self, p):
        """Original ids owned by part p (in permuted order)."""
        return self.inv_perm[self.offsets[p]:self.offsets[p + 1]]

    @property
    def edge_cut(self):
        cut = 0
        for p, (src, dst) in enumerate(self.local_edges):
            cut += int((self.part[src] != p).sum())
        return cut


def _degree_order(src, dst, num_nodes):
    """BFS order from the max-degree node (stream locality for LDG)."""
    from collections import deque
    adj_start, adj = _build_csr(src, dst, num_nodes)
    deg = np.diff(adj_start)
    order, seen = [], np.zeros(num_nodes, bool)
    queue = deque()
    for seed in np.argsort(-deg):
        if seen[seed]:
            continue
        queue.append(int(seed))
        seen[seed] = True
        while queue:
            u = queue.popleft()
            order.append(u)
            for v in adj[adj_start[u]:adj_start[u + 1]]:
                if not seen[v]:
                    seen[v] = True
                    queue.append(int(v))
    return np.asarray(order, np.int64), (adj_start, adj)


def _build_csr(src, dst, num_nodes):
    """Undirected CSR over the union of both directions."""
    u = np.concatenate([src, dst]).astype(np.int64)
    v = np.concatenate([dst, src]).astype(np.int64)
    order = np.argsort(u, kind="stable")
    u, v = u[order], v[order]
    start = np.zeros(num_nodes + 1, np.int64)
    np.add.at(start, u + 1, 1)
    start = np.cumsum(start)
    return start, v


def partition_graph(src, dst, num_nodes, nparts, *, seed=0,
                    imbalance=1.05, refine_sweeps=2):
    """Balanced low-cut partitioning (the part_graph role).

    LDG streaming: nodes arrive in BFS order; each goes to the part
    holding most of its already-placed neighbors, scaled by remaining
    capacity; then ``refine_sweeps`` boundary-move passes reduce the cut
    further under the same balance cap."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    cap = int(np.ceil(imbalance * num_nodes / nparts))
    order, (adj_start, adj) = _degree_order(src, dst, num_nodes)
    part = np.full(num_nodes, -1, np.int64)
    sizes = np.zeros(nparts, np.int64)
    rng = np.random.default_rng(seed)
    for u in order:
        neigh = adj[adj_start[u]:adj_start[u + 1]]
        placed = part[neigh]
        scores = np.zeros(nparts, np.float64)
        np.add.at(scores, placed[placed >= 0], 1.0)
        scores *= 1.0 - sizes / cap          # LDG capacity penalty
        scores[sizes >= cap] = -np.inf
        best = np.flatnonzero(scores == scores.max())
        part[u] = best[0] if len(best) == 1 else rng.choice(best)
        sizes[part[u]] += 1
    for _ in range(refine_sweeps):
        moved = 0
        for u in order:
            neigh = adj[adj_start[u]:adj_start[u + 1]]
            if len(neigh) == 0:
                continue
            counts = np.zeros(nparts, np.int64)
            np.add.at(counts, part[neigh], 1)
            tgt = int(np.argmax(counts))
            cur = int(part[u])
            if (tgt != cur and counts[tgt] > counts[cur]
                    and sizes[tgt] < cap):
                part[u] = tgt
                sizes[tgt] += 1
                sizes[cur] -= 1
                moved += 1
        if moved == 0:
            break

    # contiguous permutation: permuted order = part-major, BFS-minor
    pos_in_order = np.empty(num_nodes, np.int64)
    pos_in_order[order] = np.arange(num_nodes)
    perm_order = np.lexsort((pos_in_order, part))   # sort by (part, bfs)
    inv_perm = np.asarray(perm_order, np.int64)     # position -> orig id
    perm = np.empty(num_nodes, np.int64)
    perm[inv_perm] = np.arange(num_nodes)
    offsets = np.zeros(nparts + 1, np.int64)
    np.add.at(offsets, part + 1, 1)
    offsets = np.cumsum(offsets)

    gp = GraphPartition(nparts=nparts, num_nodes=num_nodes, part=part,
                        perm=perm, inv_perm=inv_perm, offsets=offsets)
    for p in range(nparts):
        owned = part[dst] == p
        e_src, e_dst = src[owned], dst[owned]
        gp.local_edges.append((e_src.copy(), e_dst.copy()))
        halo = np.unique(e_src[part[e_src] != p])
        gp.halos.append(halo)
    return gp


def save_partition(gp, out_dir):
    """Write meta.json + one part{p}.npz per part (part_graph's
    output-directory contract, re-shaped for numpy consumers)."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump({"nparts": gp.nparts, "num_nodes": gp.num_nodes,
                   "edge_cut": gp.edge_cut}, f)
    np.savez(os.path.join(out_dir, "global.npz"), part=gp.part,
             perm=gp.perm, inv_perm=gp.inv_perm, offsets=gp.offsets)
    for p in range(gp.nparts):
        s, d = gp.local_edges[p]
        np.savez(os.path.join(out_dir, f"part{p}.npz"),
                 src=s, dst=d, halo=gp.halos[p],
                 owned=gp.part_nodes(p))


def load_partition(out_dir):
    with open(os.path.join(out_dir, "meta.json")) as f:
        meta = json.load(f)
    g = np.load(os.path.join(out_dir, "global.npz"))
    gp = GraphPartition(nparts=meta["nparts"],
                        num_nodes=meta["num_nodes"],
                        part=g["part"], perm=g["perm"],
                        inv_perm=g["inv_perm"], offsets=g["offsets"])
    for p in range(gp.nparts):
        d = np.load(os.path.join(out_dir, f"part{p}.npz"))
        gp.local_edges.append((d["src"], d["dst"]))
        gp.halos.append(d["halo"])
    return gp
