"""GNN dataset ingestion: edge lists, the classic Cora/Citeseer citation
format, and the reference's ``graph.npz`` array convention.

Reference: examples/gnn/gnn_tools/sparse_datasets.py (AmazonSparse
``graph.npz`` with edge/y/train_map arrays; undirected doubling) and
part_graph.py (dataset → partitioner input).  The download/ogb steps are
absent by design (zero-egress environment): these loaders ingest LOCAL
files in the public formats into plain numpy arrays that feed
``partition_graph`` / ``NeighborSampler`` / the DistGCN example
directly.  A vendored Cora-format sample graph ships under
examples/gnn/datasets/ so the pipeline runs offline.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace

import numpy as np

from ..datasets._io import open_text as _open_text


@dataclass
class GraphDataset:
    """Arrays the rest of the GNN tier consumes (partitioner input)."""

    src: np.ndarray          # [E] int64 edge sources
    dst: np.ndarray          # [E] int64 edge destinations
    x: np.ndarray            # [N, F] float32 node features
    y: np.ndarray            # [N] int32 labels (-1 = unlabeled)
    train_mask: np.ndarray   # [N] bool
    val_mask: np.ndarray     # [N] bool
    test_mask: np.ndarray    # [N] bool
    num_classes: int
    name: str = "graph"

    @property
    def num_nodes(self):
        return len(self.y)

    @property
    def num_edges(self):
        return len(self.src)

    def to_undirected(self):
        """Add reverse edges and drop duplicates/self-loops (the
        reference doubles directed edges the same way)."""
        s = np.concatenate([self.src, self.dst])
        d = np.concatenate([self.dst, self.src])
        keep = s != d
        s, d = s[keep], d[keep]
        key = s.astype(np.int64) * self.num_nodes + d
        _, first = np.unique(key, return_index=True)
        return replace(self, src=s[first], dst=d[first])

    def normalize_features(self):
        """Row-normalize features (standard citation-network recipe)."""
        rs = self.x.sum(1, keepdims=True)
        rs[rs == 0] = 1.0
        return replace(self, x=(self.x / rs).astype(np.float32))


def read_edge_list(path, comments="#", delimiter=None, num_nodes=None):
    """Parse a plain edge-list text file (``src dst`` per line; SNAP
    style ``#`` comments; .gz transparent).  Returns (src, dst,
    num_nodes)."""
    src, dst = [], []
    with _open_text(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(comments):
                continue
            parts = line.split(delimiter)
            src.append(int(parts[0]))
            dst.append(int(parts[1]))
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    n = num_nodes or (int(max(src.max(), dst.max())) + 1 if len(src)
                      else 0)
    return src, dst, n


def make_split(n, seed=0, train=0.6, val=0.2):
    """Deterministic train/val/test node split by fractions."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_tr, n_val = int(n * train), int(n * val)
    tr = np.zeros(n, bool)
    va = np.zeros(n, bool)
    te = np.zeros(n, bool)
    tr[perm[:n_tr]] = True
    va[perm[n_tr:n_tr + n_val]] = True
    te[perm[n_tr + n_val:]] = True
    return tr, va, te


def load_cora(prefix, seed=0):
    """Load the classic Cora/Citeseer citation distribution format:

    - ``<prefix>.content``: ``<paper_id> <f_1..f_F> <class_label>`` per
      line (string ids, binary word features, string labels);
    - ``<prefix>.cites``: ``<cited> <citing>`` per line.

    Paper ids and labels are densely re-indexed; citations touching
    unknown papers are dropped (the classic files contain a few).
    Returns a GraphDataset with a deterministic 60/20/20 split."""
    ids, feats, labels = [], [], []
    with _open_text(prefix + ".content") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) == 1:
                parts = line.split()
            if len(parts) < 3:
                continue        # blank/malformed line (id, >=1 feat, label)
            ids.append(parts[0])
            feats.append(np.asarray(parts[1:-1], np.float32))
            labels.append(parts[-1])
    id_map = {p: i for i, p in enumerate(ids)}
    classes = {c: i for i, c in enumerate(sorted(set(labels)))}
    x = np.stack(feats)
    y = np.asarray([classes[c] for c in labels], np.int32)
    src, dst = [], []
    with _open_text(prefix + ".cites") as f:
        for line in f:
            parts = line.split()
            if len(parts) != 2:
                continue
            a, b = parts
            if a in id_map and b in id_map:
                # "<cited> <citing>": edge direction citing -> cited
                src.append(id_map[b])
                dst.append(id_map[a])
    tr, va, te = make_split(len(ids), seed)
    return GraphDataset(np.asarray(src, np.int64),
                        np.asarray(dst, np.int64), x, y, tr, va, te,
                        num_classes=len(classes),
                        name=os.path.basename(prefix))


def load_graph_npz(path, features_path=None):
    """Load the reference's ``graph.npz`` convention
    (sparse_datasets.py AmazonSparse): ``edge`` [E,2], ``y`` [N],
    ``train_map`` [N] bool; optional dense features and — our
    extension, written by save_graph_npz — a ``val_map`` so the
    val/test split survives the round trip (reference files carry only
    train_map; without val_map, val nodes land in the test mask)."""
    data = np.load(path)
    edge = data["edge"]
    if edge.shape[0] == 2 and edge.shape[1] != 2:
        edge = edge.T
    y = data["y"].reshape(-1).astype(np.int32)
    n = len(y)
    tr = data["train_map"].astype(bool) if "train_map" in data \
        else np.ones(n, bool)
    x = (np.load(features_path).astype(np.float32)
         if features_path else
         data["x"].astype(np.float32) if "x" in data
         else np.empty((n, 0), np.float32))
    va = data["val_map"].astype(bool) if "val_map" in data \
        else np.zeros(n, bool)
    return GraphDataset(edge[:, 0].astype(np.int64),
                        edge[:, 1].astype(np.int64), x, y, tr, va,
                        ~tr & ~va, num_classes=int(y.max()) + 1,
                        name=os.path.basename(os.path.dirname(path))
                        or "npz")


def save_graph_npz(ds, path):
    """Write the graph.npz convention (round-trips load_graph_npz,
    including the val/test split via the val_map extension)."""
    np.savez(path,
             edge=np.stack([ds.src, ds.dst], 1),
             y=ds.y, train_map=ds.train_mask, val_map=ds.val_mask,
             **({"x": ds.x} if ds.x.size else {}))


def make_cora_sample(out_prefix, n=300, n_feat=64, n_classes=7,
                     avg_degree=4, seed=0):
    """Write a synthetic graph in the EXACT Cora distribution format
    (string paper ids, tab-separated binary features, string labels,
    .cites pairs) — the vendored examples/gnn/datasets/cora_sample.*
    came from this with the default seed.  Communities make both the
    partitioner and the classifier learn something real."""
    rng = np.random.default_rng(seed)
    comm = rng.integers(0, n_classes, n)
    paper_ids = [str(100000 + 7 * i) for i in range(n)]
    class_names = [f"Topic_{c}" for c in range(n_classes)]
    lines = []
    for i in range(n):
        # class-correlated sparse binary word features
        base = np.zeros(n_feat, np.int64)
        on = rng.random(n_feat) < 0.05
        base[on] = 1
        span = n_feat // n_classes
        block = slice(comm[i] * span, comm[i] * span + span)
        base[block] |= (rng.random(span) < 0.4).astype(np.int64)
        lines.append("\t".join([paper_ids[i]]
                               + [str(v) for v in base]
                               + [class_names[comm[i]]]))
    with open(out_prefix + ".content", "w") as f:
        f.write("\n".join(lines) + "\n")
    cites = set()
    target = n * avg_degree // 2
    while len(cites) < target:
        u, v = rng.integers(0, n, 2)
        if u == v:
            continue
        if comm[u] == comm[v] or rng.random() < 0.1:
            cites.add((paper_ids[u], paper_ids[v]))
    with open(out_prefix + ".cites", "w") as f:
        f.write("\n".join(f"{a}\t{b}" for a, b in sorted(cites)) + "\n")
    return out_prefix
