#!/usr/bin/env python3
"""Perf-regression gate over bench.py --profile signal dicts.

Compares the CURRENT round's flat ``signals`` block (PROFILE_FULL.json,
or any JSON carrying a ``signals`` key) against a BASELINE — an explicit
``--baseline`` file, or an entry of ``benchmarks/history.jsonl`` — with
direction-aware per-signal tolerances:

* throughput signals (``*.mfu``, ``*_per_sec*``, ``*_per_s``, and the
  serving ``*concurrency`` peaks from ``bench.py --serve``): higher is
  better; a regression is current < baseline * (1 - tol_throughput).
  Wall-time signals are noisy (CPU-quick rounds especially), so the
  default tolerance is loose (25%).  Concurrency is integral and
  one-sided the same way — a paged engine admitting fewer concurrent
  requests at the same HBM budget is a capacity regression.
* static signals (``*.flops_per_step``, ``*.bytes_per_step``,
  ``hbm.*_bytes``, ``kv_hbm_bytes_per_token``): lower is better and
  deterministic for one code version + shape set, so the default
  tolerance is tight (1%) — a compiled program quietly growing
  flops/bytes, a pool growing live HBM, or the paged KV cache spending
  more bytes per live token is exactly what this gate exists to catch.
* attainment signals (``*attainment*``, from ``bench.py --slo``):
  higher is better and ONE-SIDED in absolute points on a [0, 1] scale —
  a regression is current < baseline - tol_attainment (default 0.05 =
  5 points); gains never fail.
* goodput signals (``*goodput*``, from the ``bench.py --serve`` ledger
  replay): the useful fraction of wall x chips on the same [0, 1]
  scale, gated exactly like attainment — one-sided, absolute points,
  gains never fail — because the fraction compares span time against
  wall time on the same clock (machine speed cancels), so only a real
  shift in where the time goes moves it more than the tolerance.
* error-bound signals (``*logit_div*``, from ``bench.py --serve
  --kv-dtype``): a committed numerical-divergence budget, lower is
  better and ONE-SIDED — a regression is current > baseline *
  (1 + tol_error_bound); the quantized twin drifting further from its
  f32 reference than the committed bound is a quality regression, while
  shrinking divergence never fails.
* informational signals (``*shed_fraction*``): reported, never gating —
  how much the SLO controller shed is context for the attainment
  number, not independently good or bad.
* speedup signals (``*speedup*``, from ``bench.py --serve --tp N``):
  platform-conditional — gated one-sided like throughput when the
  current round ran on a real TPU mesh, informational on CPU where the
  forced host "devices" time-share the same cores.
* planner signals (``plan_*``, from ``bench.py --plan``):
  ``plan_pred_err`` gates one-sided against the larger of the committed
  baseline grown by ``--tol-error-bound`` and the absolute 0.35
  accuracy budget; ``plan_*_iter_ms`` are lower-is-better wall-clock
  latency under the loose throughput tolerance; search runtime and the
  plan-vs-hand ratio are trend context.
* elastic signals (``elastic_*``, from ``bench.py --chaos
  --elastic``): ``elastic_recovery_s`` is lower-is-better wall-clock
  latency under the loose throughput tolerance (CPU-quick recovery
  times are noisy); ``elastic_vs_restart_goodput`` — the goodput
  MARGIN of in-place elastic recovery over a cold-restart twin — gates
  like the other goodput fractions (one-sided absolute points; the
  margin collapsing toward zero means elastic recovery stopped paying
  for itself).
* migration signals (``migrate_*``, from ``bench.py --serve --fleet
  --migrate``) — checked BEFORE the generic speedup class: the
  ``migrate_*_speedup`` ratios gate against an ABSOLUTE floor of 1.0
  rather than the baseline (the contract is "live page migration is
  never slower than the teacher-forced replay it falls back to", and
  that holds on any platform — both sides of each A/B share the same
  machine); ``migrate_bytes_per_token`` is a static wire-cost signal
  (tight tolerance — the blob quietly growing per token is a framing
  regression); the rest (drain-time ratio, prefix hit rate after a
  crash) are trend context.

Signals present on only one side are reported as notes, never failures
(new programs appear, old ones retire).  Exit status: 0 when every
shared signal is inside tolerance (or no baseline exists yet — first
round), 1 when anything regressed.  Stdlib only.

Typical use::

    python bench.py --profile --quick
    python tools/perf_diff.py                       # vs BASELINE.json
    python tools/perf_diff.py --history-index -2    # vs previous round
    python bench.py --serve --quick                 # paged-vs-slot twin
    python tools/perf_diff.py --current SERVE_FULL.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: signal-name fragments that mark a higher-is-better (throughput)
#: signal; ``_per_s`` is matched as a SUFFIX only (it is a substring of
#: the static ``*_per_step`` cost signals)
THROUGHPUT_MARKERS = (".mfu", "_per_sec", "concurrency")
THROUGHPUT_SUFFIXES = ("_per_s",)
#: higher-is-better one-sided signals compared in absolute points
ATTAINMENT_MARKERS = ("attainment",)
#: goodput fractions ([0, 1] useful share of wall x chips): the same
#: one-sided absolute-points gate as attainment — a drop past the
#: tolerance means capacity moved from useful work to a lost cause
GOODPUT_MARKERS = ("goodput",)
#: lower-is-better one-sided DIVERGENCE signals (quantized-twin
#: max-logit divergence from ``--serve --kv-dtype``): only GROWTH past
#: the committed bound fails — a quantization codec drifting is a
#: quality bug, a tighter round never is.  Checked before the generic
#: static class so the loose error tolerance (quantization error is
#: noisy across traces) doesn't inherit static's 1%.
ERROR_BOUND_MARKERS = ("logit_div",)
#: context-only signals that never gate.  Numerics signals (per-layer
#: grad/update-norm drift, anomaly counts from the NumericsMonitor) are
#: model-health evidence, not performance — history rounds carry them
#: for trend reading without ever destabilizing the gate.  TPOT
#: percentiles are wall-clock latency on shared CPUs — trend context
#: for the chunked-prefill claim, too noisy to gate.
#: ``acceptance_rate`` / ``hit_rate`` are workload properties of the
#: speculative/prefix bench traces (how often the draft agrees, how
#: often prompts share prefixes), and ``ttft`` percentiles are
#: wall-clock — all trend context, none a performance gate
INFO_MARKERS = ("shed_fraction", "numerics", "grad_norm", "update_norm",
                "update_ratio", "anomal", "tpot", "acceptance_rate",
                "hit_rate", "ttft")
#: platform-conditional signals (``serve_tp_speedup`` from ``bench.py
#: --serve --tp N``): a real speedup only exists on a real multi-chip
#: mesh — on CPU the forced host "devices" share the same cores, so the
#: ratio is machine-load noise and must not gate
SPEEDUP_MARKERS = ("speedup",)
#: live-KV-migration signals (``bench.py --serve --fleet --migrate``).
#: Checked before SPEEDUP_MARKERS: ``migrate_vs_replay_speedup``
#: contains "speedup" but gates against an ABSOLUTE 1.0 floor on every
#: platform — each A/B ran migration and replay on the same machine, so
#: the ratio is platform-independent in a way the TP speedup is not.
MIGRATION_PREFIX = "migrate_"
#: auto-parallel planner signals (``bench.py --plan``) — checked before
#: every generic class: ``plan_pred_err`` is the planner's committed
#: predicted-vs-measured iteration-time error, gated one-sided against
#: the LARGER of the committed baseline grown by tol_error_bound and an
#: absolute accuracy budget (a cost model that can no longer predict
#: what it schedules is a planner regression; shrinking error never
#: fails, and baseline noise below the budget can't trip the gate);
#: ``plan_*_iter_ms`` are wall-clock latency (lower is better, gated
#: with the loose throughput tolerance); the rest (search runtime, the
#: plan-vs-hand ratio) are trend context.
PLAN_PREFIX = "plan_"
#: absolute plan_pred_err ceiling: the ISSUE 18 acceptance budget
PLAN_PRED_ERR_BUDGET = 0.35
#: elastic-training signals (``bench.py --chaos --elastic``) — checked
#: before every generic class: ``elastic_recovery_s`` is wall-clock
#: latency (lower is better, loose tolerance), and
#: ``elastic_vs_restart_goodput`` is the elastic-over-cold-restart
#: goodput margin, gated one-sided in absolute points like the other
#: goodput fractions
ELASTIC_PREFIX = "elastic_"


def classify(name, platform=None):
    """'attainment' / 'goodput' (higher is better, absolute one-sided),
    'error_bound' (lower is better, one-sided growth), 'info' (never
    gates), 'throughput' (higher is better, ratio), 'static' (lower
    is better, ratio), or 'migration_floor' (absolute one-sided floor
    at 1.0).  Speedup signals are throughput on a real TPU mesh and
    informational anywhere else (forced-host CPU devices time-share the
    same cores)."""
    if name.startswith(ELASTIC_PREFIX):
        return "goodput" if "goodput" in name else "latency"
    if name.startswith(MIGRATION_PREFIX):
        if "speedup" in name:
            return "migration_floor"
        if "bytes_per_token" in name:
            return "static"
        return "info"
    if name.startswith(PLAN_PREFIX):
        if "pred_err" in name:
            return "plan_err_budget"
        if name.endswith("_iter_ms"):
            return "latency"
        return "info"
    if any(m in name for m in SPEEDUP_MARKERS):
        return "throughput" if platform == "tpu" else "info"
    if any(m in name for m in ATTAINMENT_MARKERS):
        return "attainment"
    if any(m in name for m in GOODPUT_MARKERS):
        return "goodput"
    if any(m in name for m in ERROR_BOUND_MARKERS):
        return "error_bound"
    if any(m in name for m in INFO_MARKERS):
        return "info"
    if (any(m in name for m in THROUGHPUT_MARKERS)
            or name.endswith(THROUGHPUT_SUFFIXES)):
        return "throughput"
    return "static"


def extract_signals(doc):
    """The flat {signal: value} dict from a PROFILE_FULL.json headline,
    a history.jsonl entry, or an already-flat dict."""
    if isinstance(doc, dict) and isinstance(doc.get("signals"), dict):
        return doc["signals"]
    if isinstance(doc, dict):
        return {k: v for k, v in doc.items()
                if isinstance(v, (int, float))}
    raise SystemExit(f"unrecognized signals document: {type(doc)}")


def load_json(path):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def load_history_entry(path, index):
    entries = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                entries.append(json.loads(line))
    if not entries:
        return None
    try:
        return entries[index]
    except IndexError:
        return None


def diff_signals(current, baseline, tol_throughput, tol_static,
                 tol_attainment=0.05, platform=None,
                 tol_error_bound=0.25):
    """Per-signal verdicts: [{signal, kind, current, baseline, ratio,
    regressed}] for shared signals, plus the one-sided names.
    ``platform`` is the CURRENT round's backend — it decides whether
    speedup signals gate (tpu) or inform (everything else)."""
    rows, only_current, only_baseline = [], [], []
    for name in sorted(set(current) | set(baseline)):
        if name not in baseline:
            only_current.append(name)
            continue
        if name not in current:
            only_baseline.append(name)
            continue
        cur, base = float(current[name]), float(baseline[name])
        kind = classify(name, platform)
        if kind in ("attainment", "goodput"):
            # absolute points, one-sided: only a DROP beyond the
            # tolerance fails (a ratio misreads a 0.02 -> 0.01 noise
            # wiggle as a 50% collapse)
            ratio = None if base == 0 else cur / base
            regressed = (base - cur) > tol_attainment
        elif kind == "error_bound":
            # one-sided GROWTH check: divergence swelling past the
            # committed bound fails; a baseline of 0 (exact twin) can't
            # scale a tolerance, and punishing any nonzero drift against
            # it would make the gate un-meetable — first nonzero rounds
            # re-commit the bound instead
            ratio = None if base == 0 else cur / base
            regressed = base > 0 and cur > base * (1.0 + tol_error_bound)
        elif kind == "migration_floor":
            # absolute one-sided floor: the migrate/replay A/B shares a
            # machine, so < 1.0 means live migration lost to the replay
            # oracle outright — a contract break, not noise.  The
            # baseline only supplies trend context.
            ratio = None if base == 0 else cur / base
            regressed = cur < 1.0
        elif kind == "plan_err_budget":
            # one-sided GROWTH past the larger of the committed error
            # grown by the error tolerance and the absolute accuracy
            # budget: a tiny committed baseline must not turn timing
            # noise into a failure, and a large one must not launder a
            # cost model drifting past the budget
            ratio = None if base == 0 else cur / base
            regressed = cur > max(base * (1.0 + tol_error_bound),
                                  PLAN_PRED_ERR_BUDGET)
        elif kind == "latency":
            # lower-is-better wall-clock, loose tolerance (same noise
            # class as throughput, opposite direction)
            ratio = None if base == 0 else cur / base
            regressed = base > 0 and cur > base * (1.0 + tol_throughput)
        elif kind == "info":
            ratio = None if base == 0 else cur / base
            regressed = False
        elif base == 0:
            # a zero baseline can't scale a tolerance; only flag a
            # static signal that became nonzero (new cost from nothing)
            regressed = kind == "static" and cur > 0
            ratio = None
        elif kind == "throughput":
            ratio = cur / base
            regressed = ratio < 1.0 - tol_throughput
        else:
            ratio = cur / base
            regressed = ratio > 1.0 + tol_static
        rows.append({"signal": name, "kind": kind,
                     "current": cur, "baseline": base,
                     "ratio": None if ratio is None else round(ratio, 4),
                     "regressed": bool(regressed)})
    return rows, only_current, only_baseline


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff bench --profile signals against a baseline")
    ap.add_argument("--current",
                    default=os.path.join(REPO, "PROFILE_FULL.json"),
                    help="current round (PROFILE_FULL.json)")
    ap.add_argument("--baseline",
                    default=None,
                    help="explicit baseline JSON (default: "
                         "benchmarks/BASELINE.json when present, else "
                         "the --history entry)")
    ap.add_argument("--history",
                    default=None,
                    help="history feed (bench --profile appends here; "
                         "default benchmarks/history.jsonl).  Passing "
                         "this explicitly makes the history entry the "
                         "baseline even when a committed BASELINE.json "
                         "exists")
    ap.add_argument("--history-index", type=int, default=-1,
                    help="which history entry is the baseline when no "
                         "--baseline file is used (-1 = latest; use -2 "
                         "when the current round is already appended)")
    ap.add_argument("--tol-throughput", type=float, default=0.25,
                    help="allowed fractional DROP of a throughput "
                         "signal (default 0.25)")
    ap.add_argument("--tol-static", type=float, default=0.01,
                    help="allowed fractional GROWTH of a static "
                         "cost/memory signal (default 0.01)")
    ap.add_argument("--tol-attainment", type=float, default=0.05,
                    help="allowed absolute DROP of an attainment "
                         "signal, in fractions of 1 (default 0.05 = "
                         "5 points)")
    ap.add_argument("--tol-error-bound", type=float, default=0.25,
                    help="allowed fractional GROWTH of an error-bound "
                         "divergence signal (default 0.25)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full verdict table as JSON")
    args = ap.parse_args(argv)

    current_doc = load_json(args.current)
    current = extract_signals(current_doc)
    platform = (current_doc.get("platform")
                if isinstance(current_doc, dict) else None)
    baseline_src = None
    baseline = None
    default_baseline = os.path.join(REPO, "benchmarks", "BASELINE.json")
    history = args.history if args.history is not None else os.path.join(
        REPO, "benchmarks", "history.jsonl")
    # precedence: explicit --baseline > explicit --history > the
    # committed BASELINE.json > the default history feed
    if args.baseline:
        baseline = extract_signals(load_json(args.baseline))
        baseline_src = args.baseline
    elif args.history is not None and os.path.exists(history):
        entry = load_history_entry(history, args.history_index)
        if entry is not None:
            baseline = extract_signals(entry)
            baseline_src = f"{history}[{args.history_index}]"
    elif args.history is None and os.path.exists(default_baseline):
        baseline = extract_signals(load_json(default_baseline))
        baseline_src = default_baseline
    elif args.history is None and os.path.exists(history):
        entry = load_history_entry(history, args.history_index)
        if entry is not None:
            baseline = extract_signals(entry)
            baseline_src = f"{history}[{args.history_index}]"
    if baseline is None:
        print(json.dumps({"status": "no_baseline",
                          "note": "no baseline/history to diff against "
                                  "— commit benchmarks/BASELINE.json or "
                                  "run bench.py --profile twice",
                          "signals": len(current)}))
        return 0

    rows, only_cur, only_base = diff_signals(
        current, baseline, args.tol_throughput, args.tol_static,
        args.tol_attainment, platform=platform,
        tol_error_bound=args.tol_error_bound)
    regressions = [r for r in rows if r["regressed"]]
    summary = {"status": "regressed" if regressions else "ok",
               "baseline": baseline_src,
               "compared": len(rows),
               "regressions": len(regressions),
               "tolerances": {"throughput": args.tol_throughput,
                              "static": args.tol_static,
                              "attainment": args.tol_attainment,
                              "error_bound": args.tol_error_bound},
               "new_signals": only_cur,
               "missing_signals": only_base}
    if args.json:
        summary["table"] = rows
        print(json.dumps(summary, indent=2))
    else:
        for r in regressions:
            print(f"REGRESSION {r['signal']} ({r['kind']}): "
                  f"{r['baseline']:.6g} -> {r['current']:.6g} "
                  f"(ratio {r['ratio']})")
        print(json.dumps(summary))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
