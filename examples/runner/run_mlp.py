"""Config-driven MLP training (reference: examples/runner/run_mlp.py).

--config local : one device, plain training
--config lar   : data-parallel over every local device (DP strategy;
                 GSPMD allreduces grads over the mesh — the reference's
                 local_allreduce.yml mode)
--config rar   : print the per-host commands a remote allreduce launch
                 would execute (remote_allreduce.yml), then run locally

Synthetic MNIST-shaped data keeps the example hermetic (no downloads).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import MLP

OPTS = {
    "sgd": lambda lr: ht.SGDOptimizer(lr),
    "momentum": lambda lr: ht.MomentumOptimizer(lr),
    "nesterov": lambda lr: ht.MomentumOptimizer(lr, nesterov=True),
    "adagrad": lambda lr: ht.AdaGradOptimizer(lr),
    "adam": lambda lr: ht.AdamOptimizer(lr),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="local",
                    choices=["local", "lar", "rar"])
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--opt", default="sgd", choices=sorted(OPTS))
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--validate", action="store_true")
    args = ap.parse_args()

    import jax

    if args.config == "rar":
        from hetu_tpu.launcher import DistConfig, launch
        cfg = DistConfig(os.path.join(os.path.dirname(__file__),
                                      "remote_allreduce.yml"))
        for host, cmd in launch(cfg, __file__, args=("--config", "lar"),
                                dry_run=True):
            print(f"[{host}] {cmd}")

    rng = np.random.default_rng(0)
    B = args.batch_size
    x = ht.placeholder_op("x", (B, 784))
    y = ht.placeholder_op("y", (B,), dtype=np.int32)
    model = MLP(dims=(784, 256, 256, 10))
    h = x
    for i, lin in enumerate(model.linears):
        h = lin(h)
        if i < len(model.linears) - 1:
            h = ht.relu_op(h)
    loss = ht.reduce_mean_op(ht.softmax_cross_entropy_sparse_op(h, y))
    opt = OPTS[args.opt](args.learning_rate)

    strategy = None
    if args.config == "lar":
        from hetu_tpu.parallel import DataParallel
        strategy = DataParallel(ndev=len(jax.devices()))
    subgraphs = {"train": [loss, opt.minimize(loss)]}
    if args.validate:
        subgraphs["validate"] = [loss]
    ex = ht.Executor(subgraphs, dist_strategy=strategy)

    # synthetic MNIST: 10 gaussian blobs in pixel space
    centers = rng.standard_normal((10, 784)).astype(np.float32)
    for step in range(args.steps):
        labels = rng.integers(0, 10, B)
        batch = (centers[labels]
                 + 0.5 * rng.standard_normal((B, 784))).astype(np.float32)
        out = ex.run("train", feed_dict={x: batch, y: labels},
                     convert_to_numpy_ret_vals=True)
        if step % 50 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(out[0]):.4f}")
    if args.validate:
        labels = rng.integers(0, 10, B)
        batch = (centers[labels]
                 + 0.5 * rng.standard_normal((B, 784))).astype(np.float32)
        out = ex.run("validate", feed_dict={x: batch, y: labels},
                     convert_to_numpy_ret_vals=True)
        print(f"validate loss {float(out[0]):.4f}")


if __name__ == "__main__":
    main()
