"""Config-driven Wide&Deep training (reference: examples/runner/run_wdl.py).

--config local : in-graph embedding (XLA gather) — the TPU-preferred path
--config lps   : embedding behind the host-RAM parameter store with a HET
                 cache (bounded-staleness reads; reference local_ps.yml's
                 hybrid mode)
--config rps   : print the per-host commands a remote PS launch would run
                 (remote_ps.yml: workers + server processes over DCN),
                 then run the lps path locally
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import WDL


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="local",
                    choices=["local", "lps", "rps"])
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-embeddings", type=int, default=100000)
    ap.add_argument("--learning-rate", type=float, default=0.01)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--cache", type=int, default=5000,
                    help="HET cache rows (PS configs)")
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args()

    if args.config == "rps":
        from hetu_tpu.launcher import DistConfig, launch
        cfg = DistConfig(os.path.join(os.path.dirname(__file__),
                                      "remote_ps.yml"))
        plan = launch(cfg, __file__, args=("--config", "lps"),
                      dry_run=True)
        for host, cmd in plan:
            print(f"[{host}] {cmd}")
        if args.dry_run:
            return

    rng = np.random.default_rng(0)
    B, F = args.batch_size, 26
    dense = ht.placeholder_op("dense", (B, 13))
    sparse = ht.placeholder_op("sparse", (B, F), dtype=np.int32)
    labels = ht.placeholder_op("labels", (B,))

    ps_emb = None
    if args.config in ("lps", "rps"):
        from hetu_tpu.ps import PSEmbedding
        ps_emb = PSEmbedding(args.num_embeddings, 16, optimizer="sgd",
                             lr=args.learning_rate,
                             cache_limit=args.cache or None)
    model = WDL(args.num_embeddings, embedding_dim=16, ps_embedding=ps_emb)
    loss = model.loss(dense, sparse, labels)
    ex = ht.Executor({"train": [
        loss, ht.AdamOptimizer(args.learning_rate).minimize(loss)]})

    # zipf-ish synthetic Criteo traffic (hot rows exercise the HET cache)
    zipf = rng.zipf(1.2, size=(args.steps, B, F))
    for step in range(args.steps):
        ids = np.minimum(zipf[step] - 1, args.num_embeddings - 1)
        feed = {dense: rng.standard_normal((B, 13)).astype(np.float32),
                sparse: ids.astype(np.int32),
                labels: rng.integers(0, 2, B).astype(np.float32)}
        out = ex.run("train", feed_dict=feed, convert_to_numpy_ret_vals=True)
        if step % 25 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(out[0]):.4f}")
    if ps_emb is not None:
        ex.subexecutor["train"].ps_synchronize()
        stats = getattr(ps_emb, "cache_stats", lambda: None)()
        if stats:
            print("HET cache stats:", stats)


if __name__ == "__main__":
    main()
