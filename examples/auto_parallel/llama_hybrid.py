"""Searched hybrid-parallel Llama training (reference:
tools/Hetu-Galvatron/galvatron/models/llama/train_dist.py — search a
per-layer (tp, dp-type, ckpt) x pipeline config, then train under it).

Profiles a Llama layer stack, runs the Galvatron search, builds the
LlamaHPLayer model under the searched config (RoPE/GQA/SwiGLU per-layer
TP x DP/FSDP, searched pipeline schedule), and runs a few training steps.

Usage (8 virtual devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/auto_parallel/llama_hybrid.py --preset tiny
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import argparse

import jax

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import jax.numpy as jnp

from hetu_tpu.galvatron import (GalvatronSearch, LayerProfile, LlamaHPLayer,
                                make_lm_hybrid_model)

PRESETS = {
    # hidden, layers, heads, kv_heads, ffn  (tiny = CI-sized)
    "tiny": (32, 4, 4, 2, 64),
    "llama-7b-ish": (4096, 32, 32, 32, 11008),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny", choices=sorted(PRESETS))
    ap.add_argument("--world", type=int, default=None)
    ap.add_argument("--mem-gb", type=float, default=16.0)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--embed-sdp", dest="embed_sdp", type=int, default=0,
                    help="FSDP-shard the embedding/head rows (reference "
                         "embed_sdp flag)")
    args = ap.parse_args()

    h, n_layers, heads, kv_heads, ffn = PRESETS[args.preset]
    world = args.world or len(jax.devices())

    # 1. profile (analytic; swap in profiler.py measurements for real runs)
    per_layer_params = 4 * h * h + 3 * h * ffn
    act_bytes = 10 * args.seq_len * h * 2
    layers = [LayerProfile(2.0, per_layer_params * 4, act_bytes)
              for _ in range(n_layers)]

    # 2. search
    from hetu_tpu.galvatron import measure_ici_gbps
    ici = measure_ici_gbps() or 100.0        # measured hardware bandwidth
    cfg = GalvatronSearch(world, args.mem_gb * (1 << 30),
                          micro_bsz=2, ici_gbps=ici).search(layers)
    print(f"searched config (ici {ici:.1f} GB/s):", cfg.to_json())

    # 3. build + train the FULL LM under the searched config: vocab-parallel
    #    embedding + RMS-normed head wrap onto the first/last stage
    #    (embed_sdp), tokens in → CE loss out (reference train_dist.py)
    specs = [LlamaHPLayer(hidden=h, heads=heads, kv_heads=kv_heads, ffn=ffn)
             for _ in range(n_layers)]
    model = make_lm_hybrid_model(args.vocab, specs, cfg,
                                 embed_sdp=args.embed_sdp, norm="rms")
    params = model.init_params(jax.random.PRNGKey(0))
    step, opt_init = model.make_train_step(lr=1e-2)
    opt_state = opt_init(params)

    kx, kt = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.randint(kx, (args.batch, args.seq_len), 0, args.vocab)
    tgt = jax.random.randint(kt, (args.batch, args.seq_len), 0, args.vocab)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, x, tgt)
        print(f"step {i} loss {float(loss):.5f} "
              f"(schedule={cfg.pipeline_type}, pp={cfg.pp_deg}, "
              f"tp={cfg.tp_sizes[0]}, sp={cfg.sp_flags[0]}, "
              f"embed_sdp={args.embed_sdp})")


if __name__ == "__main__":
    main()
