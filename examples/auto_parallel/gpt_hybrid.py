"""Searched hybrid-parallel GPT training (reference:
tools/Hetu-Galvatron/galvatron/models/gpt/train_dist.py — search a
per-layer (tp, dp-type, ckpt, sp) x pipeline config, then train the full
LM under it).

Profiles a GPT layer stack, runs the Galvatron search, wraps the searched
config with a vocab-parallel embedding + tied-or-untied LM head
(embed_sdp honored), and runs a few training steps on token data.

Usage (8 virtual devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/auto_parallel/gpt_hybrid.py --preset tiny
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import argparse

import jax

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

from hetu_tpu.galvatron import (GalvatronSearch, LayerProfile,
                                TransformerHPLayer, make_lm_hybrid_model)

PRESETS = {
    # hidden, layers, heads
    "tiny": (32, 4, 4),
    "gpt2-small-ish": (768, 12, 12),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny", choices=sorted(PRESETS))
    ap.add_argument("--world", type=int, default=None)
    ap.add_argument("--mem-gb", type=float, default=16.0)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--embed-sdp", dest="embed_sdp", type=int, default=0)
    ap.add_argument("--untied", action="store_true",
                    help="separate LM-head weights (default: GPT-2 tying "
                         "when the searched config keeps pp_deg == 1)")
    args = ap.parse_args()

    h, n_layers, heads = PRESETS[args.preset]
    world = args.world or len(jax.devices())

    per_layer_params = 12 * h * h
    act_bytes = 10 * args.seq_len * h * 2
    layers = [LayerProfile(2.0, per_layer_params * 4, act_bytes)
              for _ in range(n_layers)]

    from hetu_tpu.galvatron import measure_ici_gbps
    ici = measure_ici_gbps() or 100.0        # measured hardware bandwidth
    cfg = GalvatronSearch(world, args.mem_gb * (1 << 30),
                          micro_bsz=2, ici_gbps=ici).search(layers)
    print(f"searched config (ici {ici:.1f} GB/s):", cfg.to_json())

    specs = [TransformerHPLayer(hidden=h, heads=heads)
             for _ in range(n_layers)]
    tie = (not args.untied) and cfg.pp_deg == 1
    model = make_lm_hybrid_model(args.vocab, specs, cfg,
                                 embed_sdp=args.embed_sdp,
                                 tie_embeddings=tie)
    params = model.init_params(jax.random.PRNGKey(0))
    step, opt_init = model.make_train_step(lr=1e-2)
    opt_state = opt_init(params)

    kx, kt = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.randint(kx, (args.batch, args.seq_len), 0, args.vocab)
    tgt = jax.random.randint(kt, (args.batch, args.seq_len), 0, args.vocab)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, x, tgt)
        print(f"step {i} loss {float(loss):.5f} "
              f"(pp={cfg.pp_deg}, tp={cfg.tp_sizes[0]}, "
              f"sp={cfg.sp_flags[0]}, tied={tie})")


if __name__ == "__main__":
    main()
