"""Per-layer hybrid-parallel strategy search (reference:
tools/Hetu-Galvatron — profile, search, emit the layer config).

Profiles a transformer-ish layer stack analytically, runs the native DP
core over (tp size, DDP-vs-FSDP, activation ckpt) per layer x pipeline
degree, and prints the chosen per-layer strategy JSON.
Usage: python examples/auto_parallel/galvatron_search.py --world 8
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import argparse
import json

import jax

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

from hetu_tpu.galvatron import (LayerProfile, GalvatronSearch)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=2560)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--mem-gb", type=float, default=16.0)
    ap.add_argument("--micro-bsz", type=int, default=2)
    ap.add_argument("--out", default=None, help="write config JSON here")
    ap.add_argument("--measure", action="store_true",
                    help="profile real HP layers (time + XLA memory "
                         "ledger) and the mesh's psum bandwidth instead "
                         "of analytic estimates")
    args = ap.parse_args()

    h, s = args.hidden, args.seq_len
    if args.measure:
        from hetu_tpu.galvatron import (TransformerHPLayer,
                                        measure_ici_gbps,
                                        profile_hp_layers)
        specs = [TransformerHPLayer(hidden=h, heads=max(1, h // 64))
                 for _ in range(args.layers)]
        # profile at the REAL sequence length: compute and memory terms
        # scale super-linearly with seq, so capping here would feed the
        # search numbers from a different workload than the emitted config
        layers = profile_hp_layers(specs, batch=2, seq=s)
        ici = measure_ici_gbps() or 100.0
    else:
        per_layer_params = 12 * h * h
        act_bytes = 10 * s * h * 2      # bf16 activations per sample
        compute_ms = 2.0                 # per-layer fwd estimate
        layers = [LayerProfile(compute_ms, per_layer_params * 4, act_bytes)
                  for _ in range(args.layers)]
        ici = 100.0

    search = GalvatronSearch(args.world, args.mem_gb * (1 << 30),
                             micro_bsz=args.micro_bsz, ici_gbps=ici)
    cfg = search.search(layers)
    out = cfg.to_json()
    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
