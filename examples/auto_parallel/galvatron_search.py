"""Per-layer hybrid-parallel strategy search (reference:
tools/Hetu-Galvatron — profile, search, emit the layer config).

Profiles a transformer-ish layer stack analytically, runs the native DP
core over (tp size, DDP-vs-FSDP, activation ckpt) per layer x pipeline
degree, and prints the chosen per-layer strategy JSON.
Usage: python examples/auto_parallel/galvatron_search.py --world 8
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import argparse
import json

from hetu_tpu.galvatron import (LayerProfile, GalvatronSearch)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=2560)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--mem-gb", type=float, default=16.0)
    ap.add_argument("--micro-bsz", type=int, default=2)
    ap.add_argument("--out", default=None, help="write config JSON here")
    args = ap.parse_args()

    h, s = args.hidden, args.seq_len
    per_layer_params = 12 * h * h
    act_bytes = 10 * s * h * 2          # bf16 activations per sample
    compute_ms = 2.0                     # per-layer fwd estimate
    layers = [LayerProfile(compute_ms, per_layer_params * 4, act_bytes)
              for _ in range(args.layers)]

    search = GalvatronSearch(args.world, args.mem_gb * (1 << 30),
                             micro_bsz=args.micro_bsz)
    cfg = search.search(layers)
    out = cfg.to_json()
    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
