"""Corpus -> BERT MLM/NSP pretraining features (reference
examples/nlp/bert/create_pretraining_data.py).

Input format matches the reference: plain text, one sentence per line,
blank lines between documents.  Output: one ``.npz`` with the exact
feed arrays ``BertForPreTraining.loss`` consumes —
input_ids/token_type_ids/attention_mask [N, S], mlm_labels [N*S]
(-1 = unmasked), nsp_labels [N].

    python examples/nlp/create_pretraining_data.py \
        --input corpus.txt --vocab vocab.txt --output features.npz \
        [--max_seq_length 128] [--dupe_factor 2] [--masked_lm_prob 0.15]

Train from it:
    data = np.load("features.npz")
    ... feed slices into BertForPreTraining.loss (see examples/nlp/
    train_bert.py for the executor setup).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True,
                    help="text file(s), comma-separated")
    ap.add_argument("--vocab", required=True, help="vocab file path OR a registered name like bert-base-uncased (resolved locally via hetu_tpu.tokenizers.resolve_vocab)")
    ap.add_argument("--output", required=True)
    ap.add_argument("--max_seq_length", type=int, default=128)
    ap.add_argument("--dupe_factor", type=int, default=2)
    ap.add_argument("--short_seq_prob", type=float, default=0.1)
    ap.add_argument("--masked_lm_prob", type=float, default=0.15)
    ap.add_argument("--max_predictions_per_seq", type=int, default=20)
    ap.add_argument("--seed", type=int, default=12345)
    args = ap.parse_args()

    from hetu_tpu.datasets import (create_pretraining_arrays,
                                   documents_from_text_file)
    from hetu_tpu.tokenizers import BertTokenizer

    tok = BertTokenizer.from_pretrained(args.vocab)
    docs = []
    for path in args.input.split(","):
        docs.extend(documents_from_text_file(path, tok))
    print(f"{len(docs)} documents, "
          f"{sum(len(s) for d in docs for s in d)} tokens")
    arrays = create_pretraining_arrays(
        docs, tok, max_seq_length=args.max_seq_length,
        dupe_factor=args.dupe_factor, short_seq_prob=args.short_seq_prob,
        masked_lm_prob=args.masked_lm_prob,
        max_predictions_per_seq=args.max_predictions_per_seq,
        seed=args.seed)
    np.savez_compressed(args.output, **arrays)
    n, s = arrays["input_ids"].shape
    masked = int((arrays["mlm_labels"] >= 0).sum())
    print(f"wrote {args.output}: {n} instances x seq {s}, "
          f"{masked} masked positions, "
          f"NSP random fraction {arrays['nsp_labels'].mean():.3f}")


if __name__ == "__main__":
    main()
