"""BERT-base pretraining, MLM + NSP (reference: examples/nlp/bert).

Synthetic token streams by default (the reference's data prep pipelines
produce the same [B,S] int tensors).  bf16 compute + f32 masters; attention
runs through the Pallas flash kernel on TPU.
Usage: python examples/nlp/train_bert.py [--layers 12 --steps 30]
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np
import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models import BertConfig, BertForPreTraining


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--features", default=None,
                    help=".npz from examples/nlp/create_pretraining_data"
                         ".py — real MLM/NSP features instead of "
                         "synthetic ids")
    ap.add_argument("--vocab-size", type=int, default=30522)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    B, S = args.batch_size, args.seq_len
    data = None
    if args.features:
        with np.load(args.features) as z:
            # materialize once: NpzFile re-decompresses on every access
            data = {k: z[k] for k in z.files}
        n, S = data["input_ids"].shape
        data["mlm_labels"] = data["mlm_labels"].reshape(n, S)
        assert n >= B, f"only {n} instances for batch {B}"
        assert int(data["input_ids"].max()) < args.vocab_size, (
            "features were built with a larger vocab than --vocab-size; "
            "out-of-range ids would gather garbage embeddings silently")
        print(f"loaded {n} pretraining instances (seq {S}) from "
              f"{args.features}")
    c = BertConfig(vocab_size=args.vocab_size, hidden_size=768,
                   num_hidden_layers=args.layers, seq_len=S,
                   max_position_embeddings=max(512, S))

    input_ids = ht.placeholder_op("input_ids", (B, S), dtype=np.int32)
    token_type = ht.placeholder_op("token_type_ids", (B, S),
                                   dtype=np.int32)
    attn_mask = ht.placeholder_op("attention_mask", (B, S))
    mlm_labels = ht.placeholder_op("mlm_labels", (B * S,), dtype=np.int32)
    nsp_labels = ht.placeholder_op("nsp_labels", (B,), dtype=np.int32)

    model = BertForPreTraining(c)
    loss = model.loss(input_ids, token_type, attn_mask, mlm_labels,
                      nsp_labels)
    opt = ht.AdamWOptimizer(learning_rate=args.lr, weight_decay=0.01)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]},
                     compute_dtype=jnp.bfloat16)

    for step in range(args.steps):
        if data is not None:
            sl = rng.choice(data["input_ids"].shape[0], B, replace=False)
            feed = {input_ids: data["input_ids"][sl],
                    token_type: data["token_type_ids"][sl],
                    attn_mask: data["attention_mask"][sl],
                    mlm_labels: data["mlm_labels"][sl].reshape(-1),
                    nsp_labels: data["nsp_labels"][sl]}
        else:
            ids = rng.integers(0, c.vocab_size, (B, S))
            mlm = np.full((B * S,), -1, np.int64)
            pos = rng.random(B * S) < 0.15
            mlm[pos] = rng.integers(0, c.vocab_size, pos.sum())
            feed = {input_ids: ids,
                    token_type: rng.integers(0, 2, (B, S)),
                    attn_mask: np.ones((B, S), np.float32),
                    mlm_labels: mlm,
                    nsp_labels: rng.integers(0, 2, (B,))}
        out = ex.run("train", feed_dict=feed,
                     convert_to_numpy_ret_vals=True)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {out[0]:.4f}")


if __name__ == "__main__":
    main()
