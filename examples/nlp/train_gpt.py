"""GPT causal-LM training (reference: examples/nlp + auto_parallel gpt).

Usage: python examples/nlp/train_gpt.py [--model small --steps 20]
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np
import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel, GPT_CONFIGS


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt-small",
                    choices=list(GPT_CONFIGS))
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--layers", type=int, default=0,
                    help="override layer count (0 = model default)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=3e-4)
    args = ap.parse_args()

    base = dict(GPT_CONFIGS[args.model])
    if args.layers:
        base["num_layers"] = args.layers
    c = GPTConfig(seq_len=args.seq_len, dropout_prob=0.0, **base)
    rng = np.random.default_rng(0)
    B, S = args.batch_size, args.seq_len

    ids = ht.placeholder_op("ids", (B, S), dtype=np.int32)
    labels = ht.placeholder_op("labels", (B, S), dtype=np.int32)
    model = GPTLMHeadModel(c)
    loss = model.loss(ids, labels)
    opt = ht.AdamWOptimizer(learning_rate=args.lr, weight_decay=0.01)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]},
                     compute_dtype=jnp.bfloat16)

    for step in range(args.steps):
        tok = rng.integers(0, c.vocab_size, (B, S + 1))
        feed = {ids: tok[:, :-1], labels: tok[:, 1:]}
        out = ex.run("train", feed_dict=feed,
                     convert_to_numpy_ret_vals=True)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {out[0]:.4f}")


if __name__ == "__main__":
    main()
