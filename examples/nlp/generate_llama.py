"""Text generation with the KV-cache decoder (models/llama_decode.py).

Loads a transformers Llama checkpoint (or random-inits a preset), then
greedy- or sample-decodes.  With --hf-import and a tokenizer directory
this is an end-to-end "chat with the checkpoint" demo; without it, a
shape/throughput smoke.

Usage:
  python examples/nlp/generate_llama.py --model llama-7b --layers 2 \
      --hidden 64 --vocab 128 --max-new 16
  python examples/nlp/generate_llama.py --hf-import /path/to/llama \
      --prompt "The capital of France is" --max-new 32 --temperature 0.7
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import (LlamaConfig, LlamaForCausalLM, LLAMA_CONFIGS,
                             load_hf_llama_weights)
from hetu_tpu.models.llama_decode import greedy_generate


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-7b",
                    choices=list(LLAMA_CONFIGS))
    ap.add_argument("--layers", type=int, default=0)
    ap.add_argument("--hidden", type=int, default=0)
    ap.add_argument("--intermediate", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=0)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--hf-import", default=None)
    ap.add_argument("--prompt", default=None,
                    help="text prompt (requires --hf-import with a "
                         "tokenizer)")
    args = ap.parse_args()

    base = dict(LLAMA_CONFIGS[args.model])
    for field, val in (("num_layers", args.layers),
                       ("hidden_size", args.hidden),
                       ("intermediate_size", args.intermediate),
                       ("vocab_size", args.vocab)):
        if val:
            base[field] = val
    c = LlamaConfig(seq_len=args.prompt_len + args.max_new, **base)

    model = LlamaForCausalLM(c, name="gen")
    ids = ht.placeholder_op("gen_ids", (1, args.prompt_len),
                            dtype=np.int32)
    ex = ht.Executor([model(ids)], seed=args.seed)

    tok = None
    if args.hf_import:
        import transformers
        hf = transformers.AutoModelForCausalLM.from_pretrained(
            args.hf_import)
        load_hf_llama_weights(ex, model, hf.state_dict(), name="gen")
        tok = transformers.AutoTokenizer.from_pretrained(args.hf_import)

    if args.prompt and tok is not None:
        prompt = np.asarray(tok(args.prompt)["input_ids"],
                            np.int32)[None, :]
    else:
        prompt = np.random.default_rng(args.seed).integers(
            1, c.vocab_size, (1, args.prompt_len)).astype(np.int32)

    import time
    import jax
    import jax.numpy as jnp
    from hetu_tpu.models.llama_decode import build_greedy_decode
    moe_names = None
    if c.num_experts:
        moe_names = [{"wg": l.mlp.gate.wg.name, "w1": l.mlp.w1.name,
                      "w2": l.mlp.w2.name, "w3": l.mlp.w3.name}
                     for l in model.model.layers]
    fn = build_greedy_decode(c, args.max_new, name="gen",
                             temperature=args.temperature,
                             top_k=args.top_k, moe_names=moe_names)
    key = jax.random.key(args.seed)
    pids = jnp.asarray(prompt, jnp.int32)
    out = np.asarray(fn(ex.params, pids, key))   # compile
    t0 = time.perf_counter()
    out = np.asarray(fn(ex.params, pids, key))
    dt = time.perf_counter() - t0
    new = out[0, prompt.shape[1]:]
    print(f"{args.max_new} tokens in {dt*1e3:.1f} ms "
          f"({args.max_new/dt:.1f} tok/s, cached decode)")
    if tok is not None:
        print(tok.decode(out[0].tolist()))
    else:
        print("generated ids:", new.tolist())


if __name__ == "__main__":
    main()
