"""Seq2seq Transformer trainer (reference examples/nlp/
train_hetu_transformer.py — IWSLT-style translation loop; here the
dataset is a synthetic token-reversal task so the example is
self-contained, same loss/optimizer scheme).

    python examples/nlp/train_transformer.py --steps 200
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import Seq2SeqTransformer, TransformerConfig


def make_batch(rng, c, B):
    """Reverse-translation: target = reversed source (BOS=1, PAD=0)."""
    src = rng.integers(2, c.vocab_size, (B, c.src_len))
    lens = rng.integers(max(2, c.src_len // 2), c.src_len + 1, B)
    tgt_out = np.zeros_like(src)
    for b, L in enumerate(lens):
        src[b, L:] = c.pad_id
        tgt_out[b, :L] = src[b, :L][::-1]
    tgt_in = np.concatenate(
        [np.ones((B, 1), np.int64), tgt_out[:, :-1]], axis=1)
    tgt_in[tgt_out == c.pad_id] = c.pad_id
    return (src, tgt_in, tgt_out,
            (src != c.pad_id).astype(np.float32),
            (tgt_out != c.pad_id).astype(np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--blocks", type=int, default=2)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--d-ff", type=int, default=512)
    ap.add_argument("--seq-len", type=int, default=24)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--dropout", type=float, default=0.1)
    ap.add_argument("--generate", action="store_true",
                    help="greedy-decode a batch after training "
                         "(KV-cache decoder) and report token accuracy")
    args = ap.parse_args()

    c = TransformerConfig(vocab_size=args.vocab, d_model=args.d_model,
                          num_blocks=args.blocks, num_heads=args.heads,
                          d_ff=args.d_ff, src_len=args.seq_len,
                          tgt_len=args.seq_len,
                          dropout_rate=args.dropout)
    B = args.batch_size
    rng = np.random.default_rng(0)

    model = Seq2SeqTransformer(c)
    src = ht.placeholder_op("src", (B, c.src_len), dtype=np.int32)
    tin = ht.placeholder_op("tgt_in", (B, c.tgt_len), dtype=np.int32)
    tout = ht.placeholder_op("tgt_out", (B, c.tgt_len), dtype=np.int32)
    skeep = ht.placeholder_op("src_keep", (B, c.src_len))
    tkeep = ht.placeholder_op("tgt_keep", (B, c.tgt_len))
    loss = model.loss(src, tin, tout, skeep, tkeep)
    opt = ht.AdamOptimizer(learning_rate=args.lr)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]})

    for step in range(args.steps):
        s, ti, to, sk, tk = make_batch(rng, c, B)
        out = ex.run("train", feed_dict={src: s, tin: ti, tout: to,
                                         skeep: sk, tkeep: tk},
                     convert_to_numpy_ret_vals=True)
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step:5d}  loss {out[0]:.4f}")

    if args.generate:
        from hetu_tpu.models import seq2seq_generate
        s, _, to, sk, tk = make_batch(rng, c, B)
        gen = seq2seq_generate(ex, model, s, sk, c.tgt_len)
        acc = float((((gen == to) * tk).sum()) / tk.sum())
        print(f"greedy decode token accuracy: {acc:.3f}")
        print("src:", s[0][sk[0] > 0][:12])
        print("gen:", gen[0][:int(tk[0].sum())][:12])


if __name__ == "__main__":
    main()
