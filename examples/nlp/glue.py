"""GLUE fine-tuning from real task data (reference
examples/nlp/bert/test_glue_hetu_bert.py + glue_processor/glue.py).

Reads the published GLUE TSV layouts (SST-2, MRPC, CoLA, MNLI) through
the framework's WordPiece tokenizer, fine-tunes
``BertForSequenceClassification``, and reports dev accuracy (+F1 for
MRPC).  Weights can start from a HuggingFace BERT checkpoint
(``--hf_weights`` accepts a torch state_dict file saved with
``torch.save``) or fresh initialization.

    python examples/nlp/glue.py --task sst-2 --data_dir <glue/SST-2> \
        --vocab <bert-base-uncased-vocab.txt> [--hf_weights pytorch_model.bin]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--task", default="sst-2",
                    choices=["sst-2", "mrpc", "cola", "mnli"])
    ap.add_argument("--data_dir", required=True)
    ap.add_argument("--vocab", required=True, help="vocab file path OR a registered name like bert-base-uncased (resolved locally via hetu_tpu.tokenizers.resolve_vocab)")
    ap.add_argument("--hf_weights", default=None,
                    help="torch state_dict file of a HF BertModel/"
                         "BertForSequenceClassification")
    ap.add_argument("--max_seq_len", type=int, default=128)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=2e-5)
    ap.add_argument("--hidden", type=int, default=768)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import hetu_tpu as ht
    from hetu_tpu import metrics
    from hetu_tpu.datasets import GLUE_PROCESSORS, convert_examples_to_arrays
    from hetu_tpu.models import BertConfig, BertForSequenceClassification
    from hetu_tpu.tokenizers import BertTokenizer

    tok = BertTokenizer.from_pretrained(args.vocab)
    proc = GLUE_PROCESSORS[args.task]()
    labels = proc.labels()
    train = convert_examples_to_arrays(
        proc.train_examples(args.data_dir), labels, tok, args.max_seq_len)
    dev = convert_examples_to_arrays(
        proc.dev_examples(args.data_dir), labels, tok, args.max_seq_len)
    print(f"{args.task}: {len(train)} train / {len(dev)} dev examples")

    B, S = args.batch, args.max_seq_len
    c = BertConfig(vocab_size=len(tok.vocab), hidden_size=args.hidden,
                   num_hidden_layers=args.layers,
                   num_attention_heads=args.heads,
                   intermediate_size=4 * args.hidden, seq_len=S,
                   max_position_embeddings=max(512, S))
    ids = ht.placeholder_op("g_ids", (B, S), dtype=np.int32)
    tt = ht.placeholder_op("g_tok", (B, S), dtype=np.int32)
    am = ht.placeholder_op("g_am", (B, S))
    y = ht.placeholder_op("g_y", (B,), dtype=np.int32)
    model = BertForSequenceClassification(c, len(labels), name="glue_bert")
    loss, logits = model.loss(ids, tt, am, y)
    opt = ht.AdamWOptimizer(learning_rate=args.lr, weight_decay=0.01)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)],
                      "eval": [logits]}, seed=args.seed)

    if args.hf_weights:
        import torch
        from hetu_tpu.models.hf_import import load_hf_bert_weights
        sd = torch.load(args.hf_weights, map_location="cpu",
                        weights_only=True)
        # accept either a bare BertModel state_dict or a
        # BertForSequenceClassification one ("bert." prefixed)
        if any(k.startswith("bert.") for k in sd):
            sd = {k[len("bert."):]: v for k, v in sd.items()
                  if k.startswith("bert.")}
        load_hf_bert_weights(ex, model.bert, sd, name="glue_bert")
        print("loaded HF weights")

    def feeds(batch):
        return {ids: batch["input_ids"], tt: batch["token_type_ids"],
                am: batch["attention_mask"], y: batch["label_ids"]}

    def evaluate():
        preds, gold = [], []
        # keep the remainder: pad the last partial batch up to B (one
        # compiled shape) and trim its predictions back
        for batch in dev.batches(B, drop_remainder=False):
            n_real = len(batch["label_ids"])
            if n_real < B:
                batch = {k: np.concatenate(
                    [v, np.repeat(v[:1], B - n_real, axis=0)])
                    for k, v in batch.items()}
            out = ex.run("eval", feed_dict=feeds(batch),
                         convert_to_numpy_ret_vals=True)[0]
            preds.append(np.argmax(out, -1)[:n_real])
            gold.append(batch["label_ids"][:n_real])
        preds, gold = np.concatenate(preds), np.concatenate(gold)
        res = {"accuracy": float((preds == gold).mean())}
        if args.task == "mrpc":
            res["f1"] = metrics.f1_score(preds, gold)
        return res

    step = 0
    res = None
    for epoch in range(args.epochs):
        t0 = time.time()
        run_loss = []
        for batch in train.batches(B, shuffle=True, seed=args.seed + epoch):
            out = ex.run("train", feed_dict=feeds(batch),
                         convert_to_numpy_ret_vals=True)
            run_loss.append(float(out[0]))
            step += 1
        res = evaluate()
        print(f"epoch {epoch}: loss {np.mean(run_loss):.4f} "
              f"dev {res} ({time.time()-t0:.1f}s)")
    if res is None:               # --epochs 0: eval-only
        res = evaluate()
        print(f"eval-only dev {res}")
    return res


if __name__ == "__main__":
    main()
