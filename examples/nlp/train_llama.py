"""Llama/Baichuan causal-LM training (reference:
tools/Hetu-Galvatron/galvatron/models/llama/train.py, models/baichuan/).

Covers the graph-API training path with optional parallelism flags:
  --tp/--dp      dp x tp via the MegatronLM strategy (SwiGLU gate/up
                 column-parallel, down row-parallel)
  --pp           graph-pipeline staging (1f1b schedule)
  --hf-import    load a transformers Llama checkpoint by path

Usage: python examples/nlp/train_llama.py [--model llama-7b --layers 2]
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np
import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models import (LlamaConfig, LlamaForCausalLM, LLAMA_CONFIGS,
                             load_hf_llama_weights)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-7b",
                    choices=list(LLAMA_CONFIGS))
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--layers", type=int, default=0,
                    help="override layer count (0 = model default)")
    ap.add_argument("--hidden", type=int, default=0,
                    help="override hidden size (0 = model default)")
    ap.add_argument("--intermediate", type=int, default=0,
                    help="override FFN size (0 = model default)")
    ap.add_argument("--vocab", type=int, default=0,
                    help="override vocab size (0 = model default)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=0,
                    help="pipeline stages (graph pipeline, 1f1b)")
    ap.add_argument("--hf-import", default=None,
                    help="path to a transformers checkpoint dir to load")
    args = ap.parse_args()

    base = dict(LLAMA_CONFIGS[args.model])
    for field, val in (("num_layers", args.layers),
                       ("hidden_size", args.hidden),
                       ("intermediate_size", args.intermediate),
                       ("vocab_size", args.vocab)):
        if val:
            base[field] = val
    c = LlamaConfig(seq_len=args.seq_len, **base)
    rng = np.random.default_rng(0)
    B, S = args.batch_size, args.seq_len

    ids = ht.placeholder_op("ids", (B, S), dtype=np.int32)
    labels = ht.placeholder_op("labels", (B, S), dtype=np.int32)
    model = LlamaForCausalLM(c, pipeline_stages=args.pp or None)
    loss = model.loss(ids, labels)
    opt = ht.AdamWOptimizer(learning_rate=args.lr, weight_decay=0.01)

    kwargs = dict(compute_dtype=jnp.bfloat16)
    if args.pp:
        from hetu_tpu.parallel import make_mesh
        kwargs.update(mesh=make_mesh({"pp": args.pp}), pipeline="1f1b",
                      num_micro=max(2, args.pp))
    elif args.tp > 1 or args.dp > 1:
        from hetu_tpu.parallel import MegatronLM
        kwargs.update(dist_strategy=MegatronLM(dp=args.dp, tp=args.tp))
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]}, **kwargs)

    if args.hf_import:
        import transformers
        hf = transformers.AutoModelForCausalLM.from_pretrained(
            args.hf_import)
        load_hf_llama_weights(ex, model, hf.state_dict())
        print(f"imported weights from {args.hf_import}")

    for step in range(args.steps):
        tok = rng.integers(0, c.vocab_size, (B, S + 1))
        feed = {ids: tok[:, :-1], labels: tok[:, 1:]}
        out = ex.run("train", feed_dict=feed,
                     convert_to_numpy_ret_vals=True)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {out[0]:.4f}")


if __name__ == "__main__":
    main()
