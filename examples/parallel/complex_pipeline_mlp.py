"""Mixed DP x PP pipeline MLP (reference
examples/runner/parallel/complex_pipeline_mlp.py:1 — an MLP whose blocks
carry explicit per-device contexts mixing data/model/pipeline
parallelism, launched via config{1..8}.yml worker counts).

TPU redesign: the same mix is ONE mesh.  Blocks get `with ht.stage(i)`
scopes (the reference's per-op ctx lists); the executor runs them as a
GPipe/1F1B schedule over the mesh's leading 'pp' axis, and each stage's
remaining mesh axes form its intra-stage submesh — here 'dp', so every
stage is data-parallel over the batch (GSPMD inserts the grad psum the
reference expressed as AllReduce ops).

Run on the virtual 8-device mesh (pp=4 x dp=2):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/parallel/complex_pipeline_mlp.py
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import numpy as np

import hetu_tpu as ht
from hetu_tpu.parallel import make_mesh
from hetu_tpu.parallel.mesh import DistState


def build(stages, width, batch, tag, dp=False):
    x = ht.placeholder_op(f"cx_{tag}", (batch, width))
    y = ht.placeholder_op(f"cy_{tag}", (batch, width))
    if dp:
        # batch-sharded over the intra-stage 'dp' axis
        x.dist_state = DistState({0: "dp"})
        y.dist_state = DistState({0: "dp"})
    h = x
    for s in range(stages):
        with ht.stage(s):
            w = ht.VariableOp(f"cw{s}_{tag}", (width, width),
                              ht.init.xavier_uniform())
            b = ht.VariableOp(f"cb{s}_{tag}", (width,), ht.init.zeros())
            h = ht.relu_op(ht.matmul_op(h, w) + ht.broadcastto_op(b, h))
    loss = ht.mse_loss_op(h, y)
    return x, y, loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--num-micro", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--schedule", default="gpipe",
                    choices=["gpipe", "1f1b"])
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    X = rng.standard_normal((args.batch, args.width)).astype(np.float32)
    Y = rng.standard_normal((args.batch, args.width)).astype(np.float32)

    # ONE graph drives both executors (identical seeded init); the
    # dist_state annotations only bind when a mesh is attached
    x, y, loss = build(args.stages, args.width, args.batch, "mlp",
                       dp=args.dp > 1)
    ex_ref = ht.Executor(
        {"train": [loss, ht.AdamOptimizer(1e-2).minimize(loss)]}, seed=3)
    # pp x dp mesh: stage i owns mesh.devices[i] (a dp-row of devices)
    mesh = make_mesh({"pp": args.stages, "dp": args.dp})
    ex_pp = ht.Executor(
        {"train": [loss, ht.AdamOptimizer(1e-2).minimize(loss)]}, seed=3,
        mesh=mesh, pipeline=args.schedule, num_micro=args.num_micro)

    t0 = time.time()
    for step in range(args.steps):
        l_ref = ex_ref.run("train", feed_dict={x: X, y: Y},
                           convert_to_numpy_ret_vals=True)[0]
        l_pp = ex_pp.run("train", feed_dict={x: X, y: Y},
                         convert_to_numpy_ret_vals=True)[0]
        np.testing.assert_allclose(l_pp, l_ref, rtol=3e-5, atol=3e-6)
        if step % 3 == 0 or step == args.steps - 1:
            print(f"step {step:3d}  pp×dp loss {float(l_pp):.6f}  "
                  f"single {float(l_ref):.6f}")
    print(f"loss parity over {args.steps} steps "
          f"(pp={args.stages} x dp={args.dp}, {args.schedule}, "
          f"micro={args.num_micro}) in {time.time()-t0:.1f}s")


if __name__ == "__main__":
    main()
