"""Distributed training strategies demo (reference: examples/runner +
auto_parallel — DP / FSDP / Megatron-TP over a device mesh).

On one chip, simulate 8 devices:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/parallel/train_dp.py --strategy dp
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np
import jax

# honor JAX_PLATFORMS=cpu even when a site TPU plugin pre-registered
# (same workaround as tests/conftest.py)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import hetu_tpu as ht
from hetu_tpu.models import MLP
from hetu_tpu.parallel import DataParallel, FSDP, MegatronLM


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--strategy", default="dp",
                    choices=["dp", "fsdp", "megatron", "single"])
    ap.add_argument("--ndev", type=int, default=0,
                    help="devices (0 = all visible)")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    ndev = args.ndev or len(jax.devices())
    strategy = {"dp": lambda: DataParallel(ndev=ndev),
                "fsdp": lambda: FSDP(ndev=ndev),
                "megatron": lambda: MegatronLM(ndev=ndev),
                "single": lambda: None}[args.strategy]()

    rng = np.random.default_rng(0)
    B = args.batch_size
    x = ht.placeholder_op("x", (B, 32))
    y = ht.placeholder_op("y", (B,), dtype=np.int32)
    model = MLP(dims=(32, 128, 2))
    logits = model(x)
    loss = ht.reduce_mean_op(ht.softmax_cross_entropy_sparse_op(logits, y))
    opt = ht.SGDOptimizer(learning_rate=0.3)
    ex = ht.Executor([loss, opt.minimize(loss)], dist_strategy=strategy)

    X = rng.standard_normal((B, 32)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int64)
    for step in range(args.steps):
        out = ex.run(feed_dict={x: X, y: Y},
                     convert_to_numpy_ret_vals=True)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"[{args.strategy} x{ndev}] step {step:4d} "
                  f"loss {out[0]:.4f}")


if __name__ == "__main__":
    main()
