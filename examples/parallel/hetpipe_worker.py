"""HetPipe/preduce worker replica as a real PROCESS.

Reference: pipedream_subexecutor.py:78-88 — each worker replica runs the
pipeline schedule locally and synchronizes weights through the parameter
server (SSP-gated push/pull) or through preduce group averaging.  Here
each replica is its own OS process (spawned by tests/test_hetpipe.py or
the launcher) talking to one PSServer that holds the authoritative
weights AND the coordination plane (SSP clocks, matchmaking, group
reduce — ps/rpc.py serve_dense_params).

Usage:
  python hetpipe_worker.py <host:port> <mode> <rank> <nworkers> \
      <steps> <straggle_ms> <out_dir>
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))


def main():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from hetu_tpu.parallel import make_mesh, PipelineParallel
    from hetu_tpu.parallel.hetpipe import HetPipeTrainer, DenseParamStore
    from hetu_tpu.ps.rpc import RemoteCoordinator

    host, port = sys.argv[1].rsplit(":", 1)
    mode, rank, nworkers, steps, straggle_ms = (
        sys.argv[2], int(sys.argv[3]), int(sys.argv[4]), int(sys.argv[5]),
        float(sys.argv[6]))
    out_dir = sys.argv[7]

    # every replica builds the SAME deterministic pipeline + data
    n_stages, n_micro, mb, d = 2, 2, 4, 8
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.standard_normal((n_stages, d, d)) * 0.3,
                               jnp.float32),
              "b": jnp.zeros((n_stages, d), jnp.float32)}
    xs = jnp.asarray(rng.standard_normal((n_micro, mb, d)), jnp.float32)
    tgt = jnp.zeros_like(xs)
    mesh = make_mesh({"pp": n_stages})
    pipeline = PipelineParallel(
        mesh, lambda p, x: jnp.tanh(x @ p["w"] + p["b"]), n_stages,
        n_micro, lambda o, t: jnp.mean((o - t) ** 2))

    coord = RemoteCoordinator(host, int(port))
    kw = dict(mode=mode, lr=0.05)
    if mode == "hetpipe":
        # set_rows is idempotent with identical deterministic values, so
        # every replica may seed concurrently without a barrier
        kw["store"] = DenseParamStore.remote(host, int(port), params,
                                             seed_values=True)
        kw["ssp"] = coord
        kw["staleness"] = 1
    else:
        kw["scheduler"] = coord
        kw["reducer"] = coord
        # matchmaking window must cover the injected straggle (200ms)
        # PLUS scheduling jitter: on a loaded single-core CI box a 300ms
        # window lets the fast worker miss the straggler in every round,
        # so no full group ever forms
        kw["wait_time"] = 900.0
    trainer = HetPipeTrainer(pipeline, params, nworkers, **kw)

    losses, group_sizes = [], []
    for step in range(steps):
        if straggle_ms > 0:
            time.sleep(straggle_ms / 1e3)
        loss, params = trainer.step(rank, params, xs, tgt)
        losses.append(loss)
        if mode == "preduce":
            group_sizes.append(len(trainer.last_partner))
    trainer.mark_done(rank)

    out = {"rank": rank, "losses": losses, "group_sizes": group_sizes,
           "clocks": coord.clocks() if mode == "hetpipe" else None}
    with open(os.path.join(out_dir, f"hetpipe_{rank}.json"), "w") as f:
        json.dump(out, f)
    print(f"hetpipe worker {rank} OK", flush=True)


if __name__ == "__main__":
    main()
