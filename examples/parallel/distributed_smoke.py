"""Multi-process launch smoke worker (reference: tests/pstests/test_apis.py
spawning scheduler+server+worker processes via hetu.launcher + yaml).

Launched by `hetu_tpu.launcher.launch` (or heturun) as N separate python
processes: each initializes jax.distributed from the HETU_* env
(launcher.process_env), proves the cross-process collective plane with a
process_allgather, and proves the DCN-side PS story by pushing gradients
into a ShardedTable whose shards live in a SEPARATE server process
(ps.rpc.PSServer), then verifying every process's update landed.

Usage (what the launcher runs):
  HETU_COORDINATOR=... HETU_NUM_PROCESSES=2 HETU_PROCESS_ID=r \\
      python distributed_smoke.py <ps_host:ps_port> <out_dir>
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()


def main():
    import numpy as np
    from hetu_tpu.launcher import initialize_from_env
    from hetu_tpu.ps import RemoteTable, ShardedTable

    jax = initialize_from_env()
    from jax.experimental import multihost_utils

    pid = jax.process_index()
    nproc = jax.process_count()

    # 1. the collective plane works across the launched processes
    gathered = np.asarray(
        multihost_utils.process_allgather(np.asarray([pid], np.int32)))
    assert sorted(gathered.reshape(-1).tolist()) == list(range(nproc)), \
        gathered

    # 2. the PS plane: both workers share ONE table served by another
    #    process over TCP (DCN analogue); sgd lr=1 makes pushes visible
    host, port = sys.argv[1].rsplit(":", 1)
    remote = RemoteTable(host, int(port))
    table = ShardedTable(remote.rows, remote.dim, tables=[remote])
    key = 7
    table.push([key], np.full((1, remote.dim), float(pid + 1), np.float32))
    multihost_utils.sync_global_devices("after_push")
    row = table.lookup([key])[0]

    out = {"pid": pid, "nproc": nproc,
           "gathered": sorted(gathered.reshape(-1).tolist()),
           "row0": float(row[0])}
    with open(os.path.join(sys.argv[2], f"worker_{pid}.json"), "w") as f:
        json.dump(out, f)
    print(f"worker {pid} OK: {out}", flush=True)


if __name__ == "__main__":
    main()
