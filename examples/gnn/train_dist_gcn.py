"""Distributed GCN on a PARTITIONED graph (reference: examples/gnn
run_dist.py + gnn_tools/part_graph.py — partition the node set, then
train with each worker owning one part).

Pipeline:
  1. ``partition_graph`` cuts the nodes into ``block`` balanced parts
     (BFS-LDG streaming + refinement — the METIS/part_graph role) and
     yields a permutation making parts contiguous.
  2. The sym-normalized adjacency is built in PERMUTED order, so
     block-sharding its rows over the mesh is exactly "device p owns
     part p" — the partitioner's locality shows up as a denser block
     diagonal, i.e. less ICI traffic for the off-part columns.
  3. ``DistGCN15D`` propagates on a (block, rep) mesh; training runs a
     2-layer GCN with cross-entropy on a train split and checks LOSS
     PARITY vs the identical single-device model.

Run on the 8-device virtual mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/gnn/train_dist_gcn.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from hetu_tpu.platform import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from hetu_tpu.gnn import partition_graph
from hetu_tpu.models.gnn import normalized_adjacency


def build_train_fn(mesh, lr):
    """2-layer GCN full-batch training step over the (block, rep) mesh:
    layer = A @ (H W); adjacency tiles sharded (block, rep), features
    row-sharded over rep, psum over rep — DistGCN15D's propagation with
    the loss/grad step fused in."""

    def gcn2(params, a, h):
        def layer(h_rows, w):
            hw = jnp.matmul(h_rows, w, preferred_element_type=jnp.float32)
            partial = jnp.matmul(a, hw, preferred_element_type=jnp.float32)
            return lax.psum(partial, "rep")
        z1 = jax.nn.relu(layer(h, params["w1"]))
        # rows of z1 are block-sharded; re-gather to rep-sharded rows
        z1_rows = lax.all_gather(z1, "block", tiled=True)
        idx = lax.axis_index("rep")
        n_rep = lax.psum(1, "rep")    # axis size, any jax version
        rows = z1_rows.shape[0] // n_rep
        z1_mine = lax.dynamic_slice_in_dim(z1_rows, idx * rows, rows)
        return layer(z1_mine, params["w2"])

    def sharded_loss(params, a, h, labels, mask):
        logits = gcn2(params, a, h).astype(jnp.float32)
        ll = jax.nn.log_softmax(logits, -1)
        picked = jnp.take_along_axis(ll, labels[:, None], 1)[:, 0]
        num = lax.psum(jnp.sum(picked * mask), "block")
        den = lax.psum(jnp.sum(mask), "block")
        return -num / den

    # differentiate THROUGH shard_map: jax transposes every collective
    # (psum/all_gather) correctly, so weight grads come out replicated —
    # no hand-placed grad psums to get wrong
    loss_fn = shard_map(
        sharded_loss, mesh=mesh,
        in_specs=(P(), P("block", "rep"), P("rep", None),
                  P("block"), P("block")),
        out_specs=P())

    @jax.jit
    def step(params, a, h, labels, mask):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, a, h, labels, mask))(params)
        new = jax.tree_util.tree_map(lambda p_, g: p_ - lr * g, params,
                                     grads)
        return new, loss

    return step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="prefix of a Cora-format graph (<prefix>.content"
                         " + <prefix>.cites, e.g. examples/gnn/datasets/"
                         "cora_sample) — omit for a synthetic graph")
    ap.add_argument("--nodes", type=int, default=256)
    ap.add_argument("--edges", type=int, default=1536)
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--block", type=int, default=4)
    ap.add_argument("--rep", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    if args.data:
        # real-format ingestion (reference sparse_datasets.py role):
        # citation files -> arrays -> partitioner input
        from hetu_tpu.gnn import load_cora
        ds = load_cora(args.data).to_undirected().normalize_features()
        n = ds.num_nodes
        src, dst = ds.src, ds.dst
        labels = ds.y
        feats = ds.x
        train_mask = ds.train_mask.astype(np.float32)
        args.features = feats.shape[1]
        args.classes = ds.num_classes
        print(f"{ds.name}: {n} nodes, {ds.num_edges} edges, "
              f"{args.features} features, {args.classes} classes")
    else:
        n = args.nodes
        # planted-partition graph (communities => the partitioner has
        # structure to find, and labels correlate with features)
        comm = rng.integers(0, args.classes, n)
        src, dst = [], []
        while len(src) < args.edges:
            u, v = rng.integers(0, n, 2)
            if comm[u] == comm[v] or rng.random() < 0.1:
                src.append(u)
                dst.append(v)
        src, dst = np.asarray(src), np.asarray(dst)
        labels = comm.astype(np.int32)
        feats = (rng.standard_normal((n, args.features)).astype(np.float32)
                 + np.eye(args.classes, args.features,
                          dtype=np.float32)[comm] * 2.0)
        train_mask = (rng.random(n) < 0.7).astype(np.float32)

    gp = partition_graph(src, dst, n, args.block, seed=0)
    rand_part = rng.integers(0, args.block, n)
    rand_cut = int((rand_part[src] != rand_part[dst]).sum())
    print(f"partitioned {n} nodes into {args.block} parts: "
          f"edge cut {gp.edge_cut} (random-assignment cut ~{rand_cut})")

    # permuted-order dense normalized adjacency: block rows = parts
    a = normalized_adjacency(gp.perm[src], gp.perm[dst], n)
    h = feats[gp.inv_perm]
    y = labels[gp.inv_perm]
    m = train_mask[gp.inv_perm]

    devs = np.array(jax.devices()[:args.block * args.rep]).reshape(
        args.block, args.rep)
    mesh = Mesh(devs, ("block", "rep"))
    params = {
        "w1": jnp.asarray(rng.standard_normal(
            (args.features, args.hidden)) * 0.2, jnp.float32),
        "w2": jnp.asarray(rng.standard_normal(
            (args.hidden, args.classes)) * 0.2, jnp.float32)}
    step = build_train_fn(mesh, args.lr)

    # single-device oracle for parity
    def single_step(params):
        def loss_fn(p):
            z1 = jax.nn.relu(a @ (h @ p["w1"]))
            logits = a @ (z1 @ p["w2"])
            ll = jax.nn.log_softmax(logits, -1)
            picked = jnp.take_along_axis(ll, y[:, None], 1)[:, 0]
            return -jnp.sum(picked * m) / jnp.sum(m)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.tree_util.tree_map(
            lambda p_, g: p_ - args.lr * g, params, grads), loss

    single_step = jax.jit(single_step)
    p_dist = jax.tree_util.tree_map(jnp.asarray, params)
    p_single = jax.tree_util.tree_map(jnp.asarray, params)
    aj, hj = jnp.asarray(a), jnp.asarray(h)
    yj, mj = jnp.asarray(y), jnp.asarray(m)
    for i in range(args.steps):
        p_dist, l_d = step(p_dist, aj, hj, yj, mj)
        p_single, l_s = single_step(p_single)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  dist loss {float(l_d):.5f}  "
                  f"single {float(l_s):.5f}")
        np.testing.assert_allclose(float(l_d), float(l_s), rtol=2e-4,
                                   atol=2e-5)
    print("loss parity: distributed == single-device at every step")


if __name__ == "__main__":
    main()
