"""GCN node classification, single-device and 1.5-D distributed
(reference: examples/gnn + gpu_ops/DistGCN_15d.py).

--dist runs the (block, rep) mesh propagation; on one chip set
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np
import jax
import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models.gnn import (distgcn_15d_op, DistGCN15D,
                                 normalized_adjacency)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=256)
    ap.add_argument("--edges", type=int, default=2048)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--classes", type=int, default=7)
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--dist", action="store_true",
                    help="1.5-D mesh propagation demo after training")
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    n = args.nodes
    src = rng.integers(0, n, args.edges).astype(np.int32)
    dst = rng.integers(0, n, args.edges).astype(np.int32)

    feats = ht.placeholder_op("feats", (n, args.features))
    labels = ht.placeholder_op("labels", (n,), dtype=np.int32)
    sv = ht.Variable("src", value=src, trainable=False)
    dv = ht.Variable("dst", value=dst, trainable=False)
    w1 = ht.Variable("w1", shape=(args.features, args.hidden),
                     initializer=ht.init.xavier_normal())
    w2 = ht.Variable("w2", shape=(args.hidden, args.classes),
                     initializer=ht.init.xavier_normal())
    h1 = ht.relu_op(distgcn_15d_op(feats, w1, sv, dv, num_nodes=n))
    logits = distgcn_15d_op(h1, w2, sv, dv, num_nodes=n)
    loss = ht.reduce_mean_op(
        ht.softmax_cross_entropy_sparse_op(logits, labels))
    ex = ht.Executor({"train": [loss,
                                ht.AdamOptimizer(0.02).minimize(loss)]})

    F = rng.standard_normal((n, args.features)).astype(np.float32)
    y = rng.integers(0, args.classes, (n,))
    for step in range(args.steps):
        out = ex.run("train", feed_dict={feats: F, labels: y},
                     convert_to_numpy_ret_vals=True)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {out[0]:.4f}")

    if args.dist:
        ndev = len(jax.devices())
        block, rep = max(1, ndev // 2), min(2, ndev)
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:block * rep]).reshape(block,
                                                                  rep),
                    ("block", "rep"))
        a = normalized_adjacency(src, dst, n)
        layer = DistGCN15D(mesh)
        w1_v = ex.get_params()[w1.name]
        z = layer(jnp.asarray(a), jnp.asarray(F), w1_v)
        print(f"1.5-D propagation on {block}x{rep} mesh -> {z.shape}")


if __name__ == "__main__":
    main()
