"""Classic-zoo training entry (reference: examples/cnn/main.py --model).

Covers every model in the reference's examples/cnn/models directory:
mlp, logreg, cnn, lenet, alexnet, vgg16, vgg19, resnet18, resnet34,
rnn, lstm.  Synthetic MNIST/CIFAR-shaped data keeps it hermetic.

  python examples/cnn/main.py --model lstm --steps 50
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu import models as M

# model -> (constructor, per-sample input shape)
ZOO = {
    "mlp": (M.MLP, (784,)),
    "logreg": (M.LogReg, (784,)),
    "cnn": (M.CNN3, (1, 28, 28)),
    "lenet": (M.LeNet, (1, 28, 28)),
    "alexnet": (M.AlexNet, (1, 28, 28)),
    "vgg16": (M.vgg16, (3, 32, 32)),
    "vgg19": (M.vgg19, (3, 32, 32)),
    "resnet18": (M.resnet18, (3, 32, 32)),
    "resnet34": (M.resnet34, (3, 32, 32)),
    "rnn": (M.RNNClassifier, (28, 28)),
    "lstm": (M.LSTMClassifier, (28, 28)),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="cnn", choices=sorted(ZOO))
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--opt", default="adam",
                    choices=["sgd", "momentum", "adam"])
    args = ap.parse_args()

    build, sample_shape = ZOO[args.model]
    rng = np.random.default_rng(0)
    B = args.batch_size
    x = ht.placeholder_op("images", (B,) + sample_shape)
    y = ht.placeholder_op("labels", (B,), dtype=np.int32)
    model = build()
    logits = model(x)
    loss = ht.reduce_mean_op(ht.softmax_cross_entropy_sparse_op(logits, y))
    opt = {"sgd": lambda: ht.SGDOptimizer(args.lr),
           "momentum": lambda: ht.MomentumOptimizer(args.lr, momentum=0.9),
           "adam": lambda: ht.AdamOptimizer(args.lr)}[args.opt]()
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]})

    # separable synthetic data: class-dependent gaussian blobs
    centers = rng.standard_normal((10,) + sample_shape).astype(np.float32)
    for step in range(args.steps):
        labels = rng.integers(0, 10, B)
        imgs = (centers[labels]
                + 0.5 * rng.standard_normal(
                    (B,) + sample_shape)).astype(np.float32)
        out = ex.run("train", feed_dict={x: imgs, y: labels},
                     convert_to_numpy_ret_vals=True)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"[{args.model}] step {step:4d}  loss {out[0]:.4f}")


if __name__ == "__main__":
    main()
