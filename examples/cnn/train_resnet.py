"""ResNet-18 image classification (reference: examples/cnn).

Synthetic CIFAR-10-shaped data by default; plug a real data source into
`batches()`.  Usage: python examples/cnn/train_resnet.py [--steps 50]
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import resnet18


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    B = args.batch_size
    x = ht.placeholder_op("images", (B, 3, 32, 32))
    y = ht.placeholder_op("labels", (B,), dtype=np.int32)
    model = resnet18(num_classes=10)
    logits = model(x)
    loss = ht.reduce_mean_op(ht.softmax_cross_entropy_sparse_op(logits, y))
    acc = ht.reduce_mean_op(
        ht.equal_op(ht.cast_op(ht.argmax_op(logits, dim=1),
                               dtype=np.float32),
                    ht.cast_op(y, dtype=np.float32)))
    opt = ht.MomentumOptimizer(learning_rate=args.lr, momentum=0.9)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)],
                      "eval": [loss, acc]})

    def batches():
        while True:
            imgs = rng.standard_normal((B, 3, 32, 32)).astype(np.float32)
            labels = rng.integers(0, 10, (B,))
            yield {x: imgs, y: labels}

    it = batches()
    for step in range(args.steps):
        feed = next(it)
        out = ex.run("train", feed_dict=feed,
                     convert_to_numpy_ret_vals=True)
        if step % 10 == 0 or step == args.steps - 1:
            ev = ex.run("eval", feed_dict=feed,
                        convert_to_numpy_ret_vals=True)
            print(f"step {step:4d}  loss {out[0]:.4f}  "
                  f"eval_loss {ev[0]:.4f}  acc {ev[1]:.3f}")


if __name__ == "__main__":
    main()
