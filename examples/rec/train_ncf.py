"""NCF-family rating-model trainer (reference examples/rec/run_compressed.py
with --model mf|gmf|mlp|neumf over examples/rec/models/).

Trains on a synthetic low-rank rating matrix (MovieLens-shaped ids:
one shared table, item ids offset by num_users) with any head and any
embedding-compression method:

    python examples/rec/train_ncf.py --head neumf
    python examples/rec/train_ncf.py --head mf --method tt --compress-rate 0.25
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu import embed_compress as ec
from hetu_tpu.models import NCFModel, REC_HEADS


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--head", default="neumf", choices=sorted(REC_HEADS))
    ap.add_argument("--method", default="full", choices=ec.METHODS)
    ap.add_argument("--compress-rate", type=float, default=0.5)
    ap.add_argument("--num-users", type=int, default=4000)
    ap.add_argument("--num-items", type=int, default=2000)
    ap.add_argument("--dim", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--lr", type=float, default=1e-2)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    users, items, D, B = (args.num_users, args.num_items, args.dim,
                          args.batch_size)
    # synthetic rank-8 ratings in [1, 5]
    U = rng.standard_normal((users, 8)) * 0.5
    V = rng.standard_normal((items, 8)) * 0.5
    R = np.clip(3.0 + U @ V.T, 1.0, 5.0).astype(np.float32)

    embedding = None
    # zipf-ish synthetic id frequencies; the training loop samples ids
    # from the SAME distribution (run_compressed.py's p=freq/freq.sum()),
    # so frequency-tiered methods (adapt/mgqe/autosrh) see the hot ids
    # they sized their uncompressed tiers for
    freq = (1.0 / (1 + np.arange(users + items))) ** 1.1
    user_p = freq[:users] / freq[:users].sum()
    item_p = freq[users:] / freq[users:].sum()
    if args.method != "full":
        counts = (freq / freq.sum() * 1e6).astype(np.int64)
        embedding = ec.make_compressed_embedding(
            args.method, users + items, D,
            compress_rate=args.compress_rate, batch_size=B, num_slot=2,
            frequencies=counts, rng=rng)
    model = NCFModel(users, items, D, head=args.head, embedding=embedding)

    ids = ht.placeholder_op("ids", (B, 2), dtype=np.int32)
    labels = ht.placeholder_op("labels", (B,))
    mse, mae, _ = model(ids, labels)
    loss = mse
    if embedding is not None:
        extra = embedding.extra_loss()
        if extra is not None:
            loss = loss + 0.1 * extra
    opt = ht.AdamOptimizer(learning_rate=args.lr)
    train_nodes = [mse, mae, opt.minimize(loss)]
    # per-method training machinery, as run_compressed.py wires it
    if embedding is not None and hasattr(embedding, "codebook_update"):
        train_nodes.append(embedding.codebook_update)
    if isinstance(embedding, ec.DeepLightEmbedding):
        train_nodes.append(embedding.make_prune_op(after=train_nodes[2]))
    ex = ht.Executor({"train": train_nodes})

    for step in range(args.steps):
        u = rng.choice(users, size=B, p=user_p)
        i = rng.choice(items, size=B, p=item_p)
        feed = {ids: np.stack([u, users + i], 1).astype(np.int32),
                labels: R[u, i]}
        out = ex.run("train", feed_dict=feed, convert_to_numpy_ret_vals=True)
        if step % 20 == 0 or step == args.steps - 1:
            print(f"[{args.head}/{args.method}] step {step:4d}  "
                  f"mse {out[0]:.4f}  mae {out[1]:.4f}")


if __name__ == "__main__":
    main()
