"""Embedding-compression benchmark driver (reference:
tools/EmbeddingMemoryCompression/run_compressed.py).

Trains a CTR head over ANY of the 17 compression methods at a target
compress rate.  Usage:
    python examples/rec/run_compressed.py --method tt --compress-rate 0.1
    python examples/rec/run_compressed.py --method dpq --steps 50
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu import embed_compress as ec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--method", default="hash", choices=ec.METHODS)
    ap.add_argument("--compress-rate", type=float, default=0.25)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-embeddings", type=int, default=50000)
    ap.add_argument("--embedding-dim", type=int, default=16)
    ap.add_argument("--num-fields", type=int, default=26)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    B, F, D = args.batch_size, args.num_fields, args.embedding_dim
    # zipf-ish synthetic id frequencies (adapt/mgqe/autosrh need them)
    freq = (1.0 / (1 + np.arange(args.num_embeddings))) ** 1.1
    freq = (freq / freq.sum() * 1e6).astype(np.int64)

    layer = ec.make_compressed_embedding(
        args.method, args.num_embeddings, D,
        compress_rate=args.compress_rate, batch_size=B, num_slot=F,
        frequencies=freq, rng=rng)

    ids = ht.placeholder_op("ids", (B, F), dtype=np.int32)
    labels = ht.placeholder_op("labels", (B,))
    emb = layer(ids)
    flat = ht.array_reshape_op(emb, output_shape=(B, F * D))
    w = ht.Variable("head_w", shape=(F * D, 1),
                    initializer=ht.init.xavier_normal())
    logits = ht.array_reshape_op(ht.matmul_op(flat, w), output_shape=(B,))
    loss = ht.reduce_mean_op(
        ht.binarycrossentropywithlogits_op(logits, labels))
    extra = layer.extra_loss()
    if extra is not None:
        loss = loss + 0.1 * extra

    opt = ht.AdamOptimizer(learning_rate=args.lr)
    train_nodes = [loss, opt.minimize(loss)]
    if hasattr(layer, "codebook_update"):
        train_nodes.append(layer.codebook_update)
    if isinstance(layer, ec.DeepLightEmbedding):
        train_nodes.append(layer.make_prune_op(after=train_nodes[1]))
    ex = ht.Executor({"train": train_nodes})

    # zipf sampling of ids, as the reference profiler does
    probs = freq / freq.sum()
    for step in range(args.steps):
        feed = {ids: rng.choice(args.num_embeddings, size=(B, F), p=probs),
                labels: rng.integers(0, 2, (B,)).astype(np.float32)}
        out = ex.run("train", feed_dict=feed,
                     convert_to_numpy_ret_vals=True)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"[{args.method} @ {args.compress_rate}] "
                  f"step {step:4d}  loss {out[0]:.4f}")


if __name__ == "__main__":
    main()
