"""Mixture-of-experts training with expert parallelism (reference:
examples/moe — test_moe_top / gates over an `ep` mesh axis).

Runs on the virtual CPU mesh too:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/moe/train_moe.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np
import jax
import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.layers.moe import MoELayer
from hetu_tpu.parallel import make_mesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gate", default="top2",
                    choices=["top1", "top2", "hash"])
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--expert-act", default="gelu",
                    choices=["gelu", "swiglu"],
                    help="swiglu = Mixtral-style gated experts")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    B, S, Hd = args.batch_size, args.seq_len, args.hidden
    x = ht.placeholder_op("x", (B, S, Hd))
    y = ht.placeholder_op("y", (B, S, Hd))
    k = 1 if args.gate == "top1" else 2
    moe = MoELayer(Hd, 4 * Hd, args.experts, k=k,
                   gate=("hash" if args.gate == "hash" else "top"),
                   expert_act=args.expert_act)
    tok_ids = None
    if args.gate == "hash":
        tok_ids = ht.placeholder_op("tok_ids", (B, S), dtype=np.int32)
    out = moe(x, ids=tok_ids)
    loss = ht.mse_loss_op(out, y)
    loss = loss + 0.01 * moe.aux_loss()
    opt = ht.AdamOptimizer(learning_rate=0.01)
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]})

    for step in range(args.steps):
        feed = {x: rng.standard_normal((B, S, Hd)).astype(np.float32),
                y: rng.standard_normal((B, S, Hd)).astype(np.float32)}
        if tok_ids is not None:
            feed[tok_ids] = rng.integers(0, 30000, (B, S))
        out_v = ex.run("train", feed_dict=feed,
                       convert_to_numpy_ret_vals=True)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {out_v[0]:.4f}")


if __name__ == "__main__":
    main()
