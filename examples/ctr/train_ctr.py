"""CTR models on synthetic Criteo-shaped data (reference: examples/ctr —
wdl_criteo, dfm_criteo, dcn_criteo; 13 dense + 26 sparse features).

--ps puts the embedding table behind the HET-cached parameter store
(ps/cstable.py) instead of an in-graph Variable — the path for tables that
don't fit HBM.  Usage: python examples/ctr/train_ctr.py --model wdl
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import WDL, DeepFM, DCN, DLRM

MODELS = {"wdl": WDL, "deepfm": DeepFM, "dcn": DCN, "dlrm": DLRM}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="wdl", choices=list(MODELS))
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-embeddings", type=int, default=100000)
    ap.add_argument("--embedding-dim", type=int, default=16)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--sparse-opt", action="store_true",
                    help="lazy (IndexedSlices) in-graph embedding updates "
                         "— only touched rows read/write per step")
    ap.add_argument("--ps", action="store_true",
                    help="host-RAM PS embedding table (server-side SGD)")
    ap.add_argument("--cache", type=int, default=0,
                    help="HET cache rows (with --ps): bounded-staleness "
                         "client cache")
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    B, F = args.batch_size, 26
    dense = ht.placeholder_op("dense", (B, 13))
    sparse = ht.placeholder_op("sparse", (B, F), dtype=np.int32)
    labels = ht.placeholder_op("labels", (B,))

    ps_emb = None
    if args.ps:
        from hetu_tpu.ps import PSEmbedding
        ps_emb = PSEmbedding(args.num_embeddings, args.embedding_dim,
                             optimizer="sgd", lr=args.lr,
                             cache_limit=args.cache or None)
    model = MODELS[args.model](args.num_embeddings,
                               embedding_dim=args.embedding_dim,
                               ps_embedding=ps_emb)
    loss = model.loss(dense, sparse, labels)
    opt = ht.AdamOptimizer(learning_rate=args.lr)
    sparse_vars = ()
    if args.sparse_opt and ps_emb is not None:
        ap.error("--sparse-opt applies to the in-graph table; it is "
                 "mutually exclusive with --ps (server-side updates)")
    if args.sparse_opt and ps_emb is None:
        # lazy in-graph updates: Adam moments for untouched rows stay
        # frozen (reference OptimizersSparse.cu semantics)
        sparse_vars = [model.emb.table]
    ex = ht.Executor(
        {"train": [loss, opt.minimize(loss, sparse_vars=sparse_vars)]})

    for step in range(args.steps):
        feed = {dense: rng.standard_normal((B, 13)).astype(np.float32),
                sparse: rng.integers(0, args.num_embeddings, (B, F)),
                labels: rng.integers(0, 2, (B,)).astype(np.float32)}
        out = ex.run("train", feed_dict=feed,
                     convert_to_numpy_ret_vals=True)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  logloss {out[0]:.4f}")


if __name__ == "__main__":
    main()
