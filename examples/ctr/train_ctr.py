"""CTR models on Criteo/Avazu-format data (reference: examples/ctr —
wdl_criteo, dfm_criteo, dcn_criteo; 13 dense + 26 sparse features).

--data points at a raw Criteo ``train.txt``/``.gz`` shard (or Avazu CSV
with --dataset avazu): the real-format ingestion pipeline
(hetu_tpu/datasets/criteo.py, the reference's load_data.py contract)
parses it, label-encodes the categorical fields into one unified table,
holds out 10%, and the run reports held-out AUC per epoch — a vendored
sample shard ships at examples/ctr/datasets/criteo_sample.txt.  Without
--data the run uses synthetic Criteo-shaped batches (shape/perf smoke).

--ps puts the embedding table behind the HET-cached parameter store
(ps/cstable.py) instead of an in-graph Variable — the path for tables
that don't fit HBM.  Usage:
    python examples/ctr/train_ctr.py --model wdl \
        --data examples/ctr/datasets/criteo_sample.txt --epochs 3
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from hetu_tpu.platform import force_platform_from_env
force_platform_from_env()

import argparse

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import WDL, DeepFM, DCN, DLRM
from hetu_tpu import metrics

MODELS = {"wdl": WDL, "deepfm": DeepFM, "dcn": DCN, "dlrm": DLRM}


def build(args, num_embeddings, num_sparse, batch):
    dense = ht.placeholder_op("dense", (batch, 13))
    sparse = ht.placeholder_op("sparse", (batch, num_sparse),
                               dtype=np.int32)
    labels = ht.placeholder_op("labels", (batch,))
    ps_emb = None
    if args.ps:
        from hetu_tpu.ps import PSEmbedding
        ps_emb = PSEmbedding(num_embeddings, args.embedding_dim,
                             optimizer="sgd", lr=args.lr,
                             cache_limit=args.cache or None)
    model = MODELS[args.model](num_embeddings,
                               embedding_dim=args.embedding_dim,
                               num_sparse=num_sparse,
                               ps_embedding=ps_emb)
    loss = model.loss(dense, sparse, labels)
    logit = model(dense, sparse)
    opt = ht.AdamOptimizer(learning_rate=args.lr)
    sparse_vars = ()
    if args.sparse_opt and ps_emb is not None:
        raise SystemExit("--sparse-opt applies to the in-graph table; it "
                         "is mutually exclusive with --ps")
    if args.sparse_opt:
        # lazy in-graph updates: Adam moments for untouched rows stay
        # frozen (reference OptimizersSparse.cu semantics)
        sparse_vars = [model.emb.table]
    ex = ht.Executor(
        {"train": [loss, opt.minimize(loss, sparse_vars=sparse_vars)],
         "predict": [logit]})
    return ex, (dense, sparse, labels)


def batches(rng, n, batch, shuffle=True):
    idx = rng.permutation(n) if shuffle else np.arange(n)
    for i in range(0, n - batch + 1, batch):
        yield idx[i:i + batch]


def eval_auc(ex, ph, dense_te, sparse_te, labels_te, batch):
    """Held-out AUC over ALL test rows (AUC is rank-based, so raw logits
    work — no sigmoid needed).  The fixed-shape predict program wants
    full batches, so the tail batch is padded with repeats and the pad
    scores dropped."""
    dense, sparse, labels = ph
    n = len(labels_te)
    scores, ys = [], []
    for i in range(0, n, batch):
        sel = np.arange(i, min(i + batch, n))
        pad = batch - len(sel)
        padded = np.concatenate([sel, np.zeros(pad, np.int64)]) \
            if pad else sel
        feed = {dense: dense_te[padded], sparse: sparse_te[padded]}
        out = ex.run("predict", feed_dict=feed,
                     convert_to_numpy_ret_vals=True)
        scores.append(out[0][:len(sel)])
        ys.append(labels_te[sel])
    return metrics.auc(np.concatenate(scores), np.concatenate(ys))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="wdl", choices=list(MODELS))
    ap.add_argument("--data", default=None,
                    help="raw Criteo train.txt/.gz (or Avazu CSV with "
                         "--dataset avazu); omit for synthetic batches")
    ap.add_argument("--dataset", default="criteo",
                    choices=["criteo", "avazu"])
    ap.add_argument("--nrows", type=int, default=None,
                    help="cap on parsed rows (full Criteo is 45.8M)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-embeddings", type=int, default=100000,
                    help="table rows for the SYNTHETIC run (real data "
                         "sizes the table from the encoded features)")
    ap.add_argument("--embedding-dim", type=int, default=16)
    ap.add_argument("--steps", type=int, default=30,
                    help="synthetic-run steps")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--sparse-opt", action="store_true",
                    help="lazy (IndexedSlices) in-graph embedding updates "
                         "— only touched rows read/write per step")
    ap.add_argument("--ps", action="store_true",
                    help="host-RAM PS embedding table (server-side SGD)")
    ap.add_argument("--cache", type=int, default=0,
                    help="HET cache rows (with --ps): bounded-staleness "
                         "client cache")
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    B = args.batch_size

    if args.data is None:
        # synthetic Criteo-shaped smoke run (the original example)
        ex, (dense, sparse, labels) = build(args, args.num_embeddings,
                                            26, B)
        for step in range(args.steps):
            feed = {dense: rng.standard_normal((B, 13)).astype(np.float32),
                    sparse: rng.integers(0, args.num_embeddings, (B, 26)),
                    labels: rng.integers(0, 2, (B,)).astype(np.float32)}
            out = ex.run("train", feed_dict=feed,
                         convert_to_numpy_ret_vals=True)
            if step % 10 == 0 or step == args.steps - 1:
                print(f"step {step:4d}  logloss {out[0]:.4f}")
        return

    from hetu_tpu.datasets import process_criteo, process_avazu
    if args.dataset == "criteo":
        ((dtr, dte), (str_, ste),
         (ltr, lte)), num_features = process_criteo(args.data,
                                                    nrows=args.nrows)
    else:
        ((str_, ste), (ltr, lte)), num_features = process_avazu(
            args.data, nrows=args.nrows)
        # Avazu has no dense features; feed a zero block (the reference
        # uses per-dataset model configs — same effect, one code path)
        dtr = np.zeros((len(ltr), 13), np.float32)
        dte = np.zeros((len(lte), 13), np.float32)
    num_sparse = str_.shape[1]
    print(f"{args.dataset}: {len(ltr)} train / {len(lte)} test rows, "
          f"{num_features} features over {num_sparse} fields")
    ex, ph = build(args, num_features, num_sparse, B)
    dense, sparse, labels = ph
    for epoch in range(args.epochs):
        losses = []
        for sel in batches(rng, len(ltr), B):
            feed = {dense: dtr[sel], sparse: str_[sel], labels: ltr[sel]}
            out = ex.run("train", feed_dict=feed,
                         convert_to_numpy_ret_vals=True)
            losses.append(float(out[0]))
        auc = eval_auc(ex, ph, dte, ste, lte, B)
        print(f"epoch {epoch}  logloss {np.mean(losses):.4f}  "
              f"held-out AUC {auc:.4f}")


if __name__ == "__main__":
    main()
