"""Headline benchmark: BERT-base pretraining step throughput on one chip.

Reproduces the reference's north-star config (BASELINE.md: examples/nlp/bert
train_hetu_bert_base_dp.sh — per-device batch 64, seq 512, hidden 768,
12 layers, Adam) and measures samples/sec on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against 55 samples/sec/chip — our standing estimate
of per-A100 BERT-base seq-512 mixed-precision training throughput for the
reference's 8×A100 DP configuration (the reference publishes no absolute
numbers; BASELINE.md documents this).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

A100_BASELINE_SAMPLES_PER_SEC = 55.0


def main():
    quick = "--quick" in sys.argv
    import jax
    import jax.numpy as jnp
    import hetu_tpu as ht
    from hetu_tpu.models import BertConfig, BertForPreTraining

    on_cpu = jax.default_backend() == "cpu"
    if quick or on_cpu:
        B, S = 8, 128
        c = BertConfig(vocab_size=30522, hidden_size=768,
                       num_hidden_layers=2, seq_len=S,
                       max_position_embeddings=512)
    else:
        # the reference's headline config exactly (per-device batch 64,
        # seq 512); fits in HBM since attention runs through the Pallas
        # flash kernel (no S^2 score tensors)
        B, S = 64, 512
        c = BertConfig(vocab_size=30522, hidden_size=768,
                       num_hidden_layers=12, seq_len=S,
                       max_position_embeddings=512)

    rng = np.random.default_rng(0)
    input_ids = ht.placeholder_op("input_ids", (B, S), dtype=np.int32)
    token_type = ht.placeholder_op("token_type_ids", (B, S), dtype=np.int32)
    attn_mask = ht.placeholder_op("attention_mask", (B, S))
    mlm_labels = ht.placeholder_op("mlm_labels", (B * S,), dtype=np.int32)
    nsp_labels = ht.placeholder_op("nsp_labels", (B,), dtype=np.int32)

    model = BertForPreTraining(c)
    loss = model.loss(input_ids, token_type, attn_mask, mlm_labels,
                      nsp_labels)
    opt = ht.AdamWOptimizer(learning_rate=1e-4, weight_decay=0.01)
    # bf16 compute / f32 master weights: the MXU-native mixed precision
    ex = ht.Executor({"train": [loss, opt.minimize(loss)]},
                     compute_dtype=jnp.bfloat16)

    ids = rng.integers(0, c.vocab_size, (B, S))
    mlm = np.full((B * S,), -1, np.int64)
    mask_pos = rng.random(B * S) < 0.15
    mlm[mask_pos] = rng.integers(0, c.vocab_size, mask_pos.sum())
    feed = {input_ids: ids,
            token_type: rng.integers(0, 2, (B, S)),
            attn_mask: np.ones((B, S), np.float32),
            mlm_labels: mlm,
            nsp_labels: rng.integers(0, 2, (B,))}

    # warmup / compile
    out = ex.run("train", feed_dict=feed, convert_to_numpy_ret_vals=True)
    assert np.isfinite(out[0]), "non-finite loss"

    steps = 5 if (quick or on_cpu) else 20
    start = time.perf_counter()
    for _ in range(steps):
        out = ex.run("train", feed_dict=feed)
    jax.block_until_ready([o for o in out if o is not None])
    elapsed = time.perf_counter() - start

    samples_per_sec = steps * B / elapsed
    print(json.dumps({
        "metric": "bert_base_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec",
        "vs_baseline": round(samples_per_sec / A100_BASELINE_SAMPLES_PER_SEC,
                             3),
    }))


if __name__ == "__main__":
    main()
